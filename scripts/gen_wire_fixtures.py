#!/usr/bin/env python
"""Golden wire-fixture generator (run manually; ARTIFACTS are committed).

Produces byte captures under tests/fixtures/wire/ from the REFERENCE's own
.proto files (/root/reference/proto, compiled with protoc into a scratch
module — deliberately NOT this repo's pb2), plus a consistent-hash
placement table derived from replicated_hash.go:81-118's algorithm with a
local FNV-1/FNV-1a implementation written from the FNV spec (offset basis
0xcbf29ce484222325, prime 0x100000001b3).  tests/test_wire_fixtures.py then
pins this repo's C++ codec, pb2 path, and vnode ring against bytes and
placements no repo codec produced — drift in any of them breaks the pin.

Usage (requires the reference checkout + protoc + python protobuf):

    mkdir -p /tmp/refpb
    protoc -I/root/reference/proto \
        -I$(python -c 'import google.api, os, sys; \
            sys.stdout.write(os.path.dirname(os.path.dirname(os.path.dirname(google.api.__file__))))') \
        gubernator.proto peers.proto --python_out=/tmp/refpb
    python scripts/gen_wire_fixtures.py /tmp/refpb
"""
import hashlib
import json
import os
import sys

OUT = os.path.join(os.path.dirname(__file__), "..", "tests", "fixtures",
                   "wire")

_MASK = (1 << 64) - 1
_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3


def fnv1_64(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h = (h * _FNV_PRIME) & _MASK
        h ^= b
    return h


def fnv1a_64(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME) & _MASK
    return h


def placement(peers, keys, hash_fn, replicas=512):
    """(key -> owner grpc address) exactly as replicated_hash.go computes
    it: vnode points hash_fn(str(i) + md5hex(addr)), binary search for the
    first point >= hash_fn(key), wrapping to 0."""
    points = []
    for addr in peers:
        digest = hashlib.md5(addr.encode()).hexdigest()
        for i in range(replicas):
            points.append((hash_fn(f"{i}{digest}".encode()), addr))
    points.sort(key=lambda p: p[0])
    hashes = [p[0] for p in points]
    out = {}
    import bisect

    for k in keys:
        idx = bisect.bisect_left(hashes, hash_fn(k.encode()))
        if idx == len(points):
            idx = 0
        out[k] = points[idx][1]
    return out


REQS = [
    dict(name="requests_per_sec", unique_key="account:1234", hits=1,
         limit=100, duration=60_000, algorithm=0, behavior=0, burst=0),
    # Varint edges: negative int64 (10-byte varint), int64 max, GLOBAL |
    # RESET_REMAINING flags.
    dict(name="a", unique_key="b", hits=-1, limit=(1 << 63) - 1,
         duration=1, algorithm=1, behavior=10, burst=25),
    dict(),  # all proto3 defaults -> empty nested message
    dict(name="café", unique_key="ключ🔑", hits=(1 << 31) - 1,
         limit=1 << 31, duration=3_600_000, algorithm=0, behavior=0,
         burst=0),
    dict(name="over", unique_key="x" * 300, hits=0, limit=5,
         duration=604_800_000, algorithm=1, behavior=64, burst=5),
]

RESPS = [
    dict(status=1, limit=100, remaining=0, reset_time=1_700_000_060_000,
         error="", metadata={"owner": "10.0.0.1:81"}),
    dict(status=0, limit=(1 << 63) - 1, remaining=(1 << 62),
         reset_time=(1 << 53), error="", metadata={}),
    dict(status=0, limit=0, remaining=0, reset_time=0,
         error="field 'unique_key' cannot be empty", metadata={}),
    dict(status=0, limit=20, remaining=19, reset_time=1_700_000_000_123,
         error="", metadata={"tier": "sketch", "owner": "10.0.0.2:81"}),
]

UPDATES = [
    dict(key="rate_check_account:1234",
         status=dict(status=1, limit=100, remaining=0,
                     reset_time=1_700_000_060_000, error="", metadata={}),
         algorithm=1),
    dict(key="a_b",
         status=dict(status=0, limit=(1 << 63) - 1, remaining=7,
                     reset_time=123, error="", metadata={}),
         algorithm=0),
]

PEERS = ["10.0.0.1:81", "10.0.0.2:81", "10.0.0.3:81", "10.0.0.4:81"]

KEYS = (
    ["requests_per_sec_account:1234", "a_b", "café_ключ🔑",
     "over_" + "x" * 300, "rate_check_account:1234"]
    + [f"key{i}" for i in range(27)]
)


def main() -> None:
    sys.path.insert(0, sys.argv[1] if len(sys.argv) > 1 else "/tmp/refpb")
    import gubernator_pb2 as rpb
    import peers_pb2 as ppb

    os.makedirs(OUT, exist_ok=True)

    def mkreq(d):
        return rpb.RateLimitReq(**d)

    def mkresp(d):
        m = rpb.RateLimitResp(
            status=d["status"], limit=d["limit"],
            remaining=d["remaining"], reset_time=d["reset_time"],
            error=d["error"],
        )
        for k, v in d["metadata"].items():
            m.metadata[k] = v
        return m

    files = {}

    def emit(fname, msg):
        data = msg.SerializeToString()
        with open(os.path.join(OUT, fname), "wb") as f:
            f.write(data)
        files[fname] = len(data)

    emit("getratelimits_req.bin",
         rpb.GetRateLimitsReq(requests=[mkreq(d) for d in REQS]))
    emit("getratelimits_resp.bin",
         rpb.GetRateLimitsResp(responses=[mkresp(d) for d in RESPS]))
    emit("getpeerratelimits_req.bin",
         ppb.GetPeerRateLimitsReq(requests=[mkreq(d) for d in REQS]))
    emit("getpeerratelimits_resp.bin",
         ppb.GetPeerRateLimitsResp(
             rate_limits=[mkresp(d) for d in RESPS]))
    emit("updatepeerglobals_req.bin",
         ppb.UpdatePeerGlobalsReq(globals=[
             ppb.UpdatePeerGlobal(
                 key=u["key"], status=mkresp(u["status"]),
                 algorithm=u["algorithm"],
             )
             for u in UPDATES
         ]))

    manifest = {
        "note": "generated by scripts/gen_wire_fixtures.py from the "
                "reference protos; do not regenerate casually — these pin "
                "wire compatibility",
        "files": files,
        "requests": REQS,
        "responses": RESPS,
        "updates": UPDATES,
        "placement": {
            "peers": PEERS,
            "replicas": 512,
            "fnv1": placement(PEERS, KEYS, fnv1_64),
            "fnv1a": placement(PEERS, KEYS, fnv1a_64),
        },
    }
    with open(os.path.join(OUT, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1, ensure_ascii=False, sort_keys=True)
    print("wrote", OUT, files)


if __name__ == "__main__":
    main()
