#!/usr/bin/env python
"""Perf-regression CI gate: diff two BENCH_E2E artifacts (ROADMAP item
5's down payment — a slow PR fails loudly instead of drifting).

Compares the NEW artifact's per-config p50 against the BASELINE's on
MATCHING keys — (config, serve_mode, concurrency) for bench_e2e rows,
plus (scenario, phase, platform) for gubload scenario rows (a scenario
key with no baseline warns instead of failing) — and fails (exit 1)
when any matched config's p50 regressed by more than --threshold
(default 25%).  Throughput (checks_per_sec) regressions past the same
threshold are reported as warnings: p50 is the gate (the tail is what
operators feel), throughput is rig-noise-prone.

Platform honesty: artifacts record the ACTUAL jax platform.  When the
two artifacts' platforms differ (e.g. a cpu CI runner diffing a tpu rig
recording), every finding downgrades to a warning and the gate exits 0
— a cross-platform diff measures the platform, not the PR.  `--warn-
only` forces the same downgrade for same-platform diffs (e.g. a fresh
CI-runner artifact vs a committed one recorded on different hardware).

Noise honesty: CPU artifacts carry multi-ms scheduler noise on the
small-batch configs (the r09/r10 depth sweeps bounce ±30% between
identical-code runs), so on cpu-vs-cpu diffs a p50 regression must
clear BOTH the relative threshold and an absolute floor
(--min-delta-ms, default 5).  TPU diffs gate on the relative threshold
alone — that is the 2ms-SLO regime where half a millisecond is a real
regression, and the floor defaults to 0 there.

Usage:
    bench_gate.py BASELINE.json NEW.json [--threshold 0.25] [--warn-only]
    bench_gate.py --repo .       # auto-pick the two latest committed
                                 # BENCH_E2E_r{N}.json artifacts
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

# Configs with a meaningful, comparable p50 (per-line "config" values).
# Sweep stage/budget lines carry no latency; client sweeps measure the
# client's machinery and are gated by the same key rule when present.
_SKIP_CONFIGS = {
    "summary", "budget_us_per_1000", "serve_sweep_stages",
    "pipeline_sweep_stages", "mesh_serve_sweep_stages",
    "client_mode_budget", "colocated_latency_bound",
}


def _key(line: dict):
    # Scenario rows (gubload artifacts, config == "load_scenario")
    # extend the key with (scenario, phase, platform): each phase of
    # each scenario gates independently, and a row only ever matches a
    # baseline recorded on the same hardware.
    return (
        line.get("config"),
        line.get("serve_mode"),
        line.get("pipeline_depth"),
        line.get("client_mode"),
        line.get("concurrency"),
        line.get("scenario"),
        line.get("phase"),
        line.get("platform"),
    )


def _latency_lines(artifact: dict):
    out = {}
    for line in artifact.get("results", []):
        cfg = line.get("config")
        if not cfg or cfg in _SKIP_CONFIGS:
            continue
        if "p50_ms" not in line or "error" in line:
            continue
        # Last line wins for repeated keys (re-runs within a sweep are
        # successive refinements of the same config).
        out[_key(line)] = line
    return out


def _round_no(path: Path) -> int:
    m = re.match(r"BENCH_E2E_r(\d+)\.json$", path.name)
    return int(m.group(1)) if m else -1


def find_latest_pair(repo: Path):
    """The two most recent committed BENCH_E2E_r{N}.json (suffix-free)
    artifacts — the PR-vs-previous-round diff the CI gate runs."""
    arts = sorted(
        (p for p in repo.glob("BENCH_E2E_r*.json") if _round_no(p) >= 0),
        key=_round_no,
    )
    if len(arts) < 2:
        raise SystemExit(
            f"bench_gate: need >= 2 BENCH_E2E_r*.json under {repo}, "
            f"found {[p.name for p in arts]}"
        )
    return arts[-2], arts[-1]


def gate(baseline: dict, new: dict, threshold: float,
         warn_only: bool, min_delta_ms: float = None) -> int:
    base_platform = baseline.get("platform", "?")
    new_platform = new.get("platform", "?")
    cross = base_platform != new_platform
    if cross:
        print(
            f"bench_gate: platform mismatch ({base_platform!r} -> "
            f"{new_platform!r}) — warn-only (a cross-platform diff "
            "measures the platform, not the PR)"
        )
    soft = cross or warn_only
    if min_delta_ms is None:
        # The platform-conditional noise floor (module docstring): cpu
        # p50s carry multi-ms scheduler noise; tpu gates on the
        # relative threshold alone.
        min_delta_ms = 5.0 if (
            base_platform == "cpu" and new_platform == "cpu"
        ) else 0.0

    base_lines = _latency_lines(baseline)
    new_lines = _latency_lines(new)
    matched = sorted(
        set(base_lines) & set(new_lines), key=lambda k: str(k)
    )
    # A scenario key with no baseline is a NEW scenario (or a platform
    # change): its first artifact becomes the baseline for the next
    # round — warn, never fail (there is nothing to regress against).
    for k in sorted(set(new_lines) - set(base_lines), key=str):
        if new_lines[k].get("scenario"):
            label = "/".join(str(p) for p in k if p is not None)
            print(
                f"bench_gate: WARN new scenario key {label}: no "
                "baseline — recorded for the next round, not gated"
            )
    if not matched:
        print("bench_gate: no matching (config, mode) keys — nothing "
              "to gate (artifact schema drift?)")
        return 0

    failures = 0
    for k in matched:
        b, n = base_lines[k], new_lines[k]
        bp50, np50 = float(b["p50_ms"]), float(n["p50_ms"])
        label = "/".join(str(p) for p in k if p is not None)
        if (
            bp50 > 0
            and np50 > bp50 * (1.0 + threshold)
            and np50 - bp50 > min_delta_ms
        ):
            kind = "WARN" if soft else "FAIL"
            print(
                f"bench_gate: {kind} {label}: p50 {bp50:.3f}ms -> "
                f"{np50:.3f}ms (+{(np50 / bp50 - 1) * 100:.0f}% > "
                f"{threshold * 100:.0f}%)"
            )
            if not soft:
                failures += 1
            continue
        bt = float(b.get("checks_per_sec") or 0)
        nt = float(n.get("checks_per_sec") or 0)
        if bt > 0 and nt < bt * (1.0 - threshold):
            print(
                f"bench_gate: WARN {label}: throughput {bt:.0f} -> "
                f"{nt:.0f} checks/s "
                f"(-{(1 - nt / bt) * 100:.0f}%; informational)"
            )
        else:
            print(
                f"bench_gate: ok   {label}: p50 {bp50:.3f} -> "
                f"{np50:.3f}ms"
            )
    print(
        f"bench_gate: {len(matched)} config(s) compared, "
        f"{failures} regression(s) past {threshold * 100:.0f}%"
    )
    return 1 if failures else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("baseline", nargs="?", help="baseline artifact")
    ap.add_argument("new", nargs="?", help="new artifact")
    ap.add_argument(
        "--repo", default=None,
        help="auto-pick the two latest committed BENCH_E2E_r{N}.json "
        "from this directory instead of naming artifacts",
    )
    ap.add_argument("--threshold", type=float, default=0.25,
                    help="p50 regression fraction that fails (0.25)")
    ap.add_argument("--min-delta-ms", type=float, default=None,
                    help="absolute p50 noise floor a regression must "
                    "also clear (default: 5 for cpu-vs-cpu diffs, 0 "
                    "otherwise)")
    ap.add_argument("--warn-only", action="store_true",
                    help="report regressions but always exit 0")
    args = ap.parse_args(argv)

    if args.repo is not None:
        base_p, new_p = find_latest_pair(Path(args.repo))
    elif args.baseline and args.new:
        base_p, new_p = Path(args.baseline), Path(args.new)
    else:
        ap.error("name BASELINE and NEW artifacts, or pass --repo")
    print(f"bench_gate: {base_p.name} (baseline) vs {new_p.name} (new)")
    baseline = json.loads(base_p.read_text())
    new = json.loads(new_p.read_text())
    return gate(baseline, new, args.threshold, args.warn_only,
                args.min_delta_ms)


if __name__ == "__main__":
    sys.exit(main())
