"""CI smoke: the gubstat observability plane end-to-end on a 3-daemon
cluster (docs/observability.md).

Asserts, strictly from the HTTP surface (/debug/vars, /metrics,
/debug/key) — never from test-internal state:

  1. census sampling: every node's /debug/vars grows a `table` block
     (the sampler ticking inside the daemon loop) and /metrics exports
     the gubernator_table_* families;
  2. tenant attribution: the cluster-wide merged ledger (gubtop's own
     merge over per-node local-serve counters) reproduces the driven
     admissions EXACTLY — allowed == admitted hits, denied == rejected
     hits — because forwarded responses are only counted by the owner;
  3. gubtop renders one cluster screen (module call, no subprocess)
     showing every node and the driven tenant;
  4. /debug/key owner routing: a non-owner answers for an owned key via
     one proxy hop, the decoded row matches the driven arithmetic, and
     the read is non-mutating (bit-identical second response);
  5. occupancy is conserved across a reshard JOIN: every driven row is
     still found exactly once (same remaining, via owner routing) after
     a fourth daemon joins, the joiner's census shows the moved rows
     resident, and the demoted owner no longer holds them.

On any failure each daemon's flight recorder dumps its ring to
GUBER_FLIGHTREC_DIR (default stats-smoke-dumps/) so the CI artifact
step can pick them up.

Run from the repo root:  python scripts/stats_smoke.py [--seed N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Runnable from a checkout without an installed package.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LIMIT = 100
HOT_LIMIT = 5
DURATION = 60_000
KEYS = 10
HITS_PER_KEY = 3
TENANT = "smoketen"


def _dump_flightrec(cluster, extra, reason: str) -> None:
    for d in list(cluster.daemons) + list(extra):
        if d.flightrec is not None:
            path = cluster.run(d.flightrec.dump(reason))
            print(f"flightrec dump ({d.grpc_address}): {path}")


def _get(addr: str, path: str, timeout: float = 5.0) -> dict:
    with urllib.request.urlopen(
        f"http://{addr}{path}", timeout=timeout
    ) as r:
        return json.loads(r.read().decode())


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=1337)
    args = ap.parse_args()

    from dataclasses import replace

    from gubernator_tpu.cli import gubtop
    from gubernator_tpu.client import V1Client
    from gubernator_tpu.core.config import (
        DaemonConfig,
        StatsConfig,
        fast_test_behaviors,
    )
    from gubernator_tpu.core.types import PeerInfo, RateLimitReq, Status
    from gubernator_tpu.daemon import Daemon
    from gubernator_tpu.net.replicated_hash import (
        ReplicatedConsistentHash,
        xx_64,
    )
    from gubernator_tpu.testing import Cluster
    from gubernator_tpu.testing.cluster import TEST_DEVICE

    conf = DaemonConfig(
        stats=StatsConfig(interval_s=0.3),
        flightrec=True,
        flightrec_dir=os.environ.get(
            "GUBER_FLIGHTREC_DIR", "stats-smoke-dumps"
        ),
    )
    cluster = Cluster.start_with(["", "", ""], conf_template=conf)
    extra = []
    try:
        http = [d.http_address for d in cluster.daemons]

        # ---- drive: KEYS keys x HITS_PER_KEY admitted hits, plus one
        # hot key saturated past its limit so `denied` is non-zero.
        keys = [f"k{i}" for i in range(KEYS)]
        cl = V1Client(cluster.addresses()[0])
        denied = 0
        try:
            for k in keys:
                for _ in range(HITS_PER_KEY):
                    r = cl.get_rate_limits([RateLimitReq(
                        name=TENANT, unique_key=k, hits=1,
                        limit=LIMIT, duration=DURATION,
                    )], timeout=30)[0]
                    assert r.error == "", r
                    assert r.status == Status.UNDER_LIMIT, r
            for _ in range(HOT_LIMIT + 3):
                r = cl.get_rate_limits([RateLimitReq(
                    name=TENANT, unique_key="hot", hits=1,
                    limit=HOT_LIMIT, duration=DURATION,
                )], timeout=30)[0]
                assert r.error == "", r
                if r.status == Status.OVER_LIMIT:
                    denied += 1
        finally:
            cl.close()
        assert denied == 3, f"hot key denied {denied} != 3"
        allowed = KEYS * HITS_PER_KEY + HOT_LIMIT

        # ---- 1: census sampling on every node -----------------------
        # The first census ticks pay the jit compile, so a post-traffic
        # sample may lag; freshness is part of the wait condition — the
        # cluster-wide LIVE count must account for every driven row
        # (occupancy additionally counts expired residents, e.g. the
        # boot warmup row, so `live` is the exact quantity here).
        deadline = time.monotonic() + 30.0
        scrapes = {}
        while True:
            scrapes = {a: gubtop.scrape(a) for a in http}
            sampled = all(
                v.get("table", {}).get("samples", 0) >= 1
                and "tenants" in v
                for v in scrapes.values()
            )
            total_live = sum(
                v.get("table", {}).get("live", 0)
                for v in scrapes.values()
            )
            if sampled and total_live >= KEYS + 1:
                break
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"census never accounted for the driven rows "
                    f"(live {total_live} < {KEYS + 1}): "
                    f"{[(a, v.get('table')) for a, v in scrapes.items()]}"
                )
            time.sleep(0.1)
        for a in http:
            with urllib.request.urlopen(
                f"http://{a}/metrics", timeout=5
            ) as r:
                body = r.read().decode()
            for fam in ("gubernator_table_occupancy",
                        "gubernator_table_bucket_fill",
                        "gubernator_tenant_hits"):
                assert fam in body, f"{fam} missing from {a}/metrics"

        # ---- 2: exact cluster-wide tenant attribution ---------------
        merged = {
            t["name"]: t
            for t in gubtop._merge_tenants(scrapes, KEYS + 4)
        }
        t = merged.get(TENANT)
        assert t is not None, f"tenant {TENANT} not in merged top-K"
        assert t["allowed"] == allowed, (
            f"merged allowed {t['allowed']} != driven {allowed}"
        )
        assert t["denied"] == denied, (
            f"merged denied {t['denied']} != driven {denied}"
        )

        # ---- 3: gubtop renders the cluster --------------------------
        screen = gubtop.render(http, top_k=5)
        assert TENANT in screen, screen
        for a in http:
            assert a in screen, f"node {a} missing from gubtop:\n{screen}"

        # ---- 4: /debug/key owner routing, non-mutating --------------
        probe = next(
            k for k in keys
            if not cluster.daemons[0].service._owns_key(f"{TENANT}_{k}")
        )
        q = f"/debug/key?name={TENANT}&key={probe}"
        first = _get(http[0], q)
        assert first.get("proxied_via") == http[0], first
        assert first["found"] is True, first
        assert first["row"]["remaining"] == float(
            LIMIT - HITS_PER_KEY
        ), first["row"]
        second = _get(http[0], q)
        second.pop("proxied_via", None)
        first.pop("proxied_via", None)
        assert first == second, (
            f"/debug/key mutated the row:\n{first}\n{second}"
        )

        # ---- 5: occupancy conserved across a reshard JOIN -----------
        pre_rows = {
            k: _get(http[0], f"/debug/key?name={TENANT}&key={k}")["row"]
            for k in keys + ["hot"]
        }

        async def boot():
            c = replace(
                conf,
                grpc_listen_address="127.0.0.1:0",
                http_listen_address="127.0.0.1:0",
                behaviors=fast_test_behaviors(),
                device=TEST_DEVICE,
            )
            d = Daemon(c)
            await d.start()
            d.conf.advertise_address = d.grpc_address
            return d

        d3 = cluster.run(boot(), timeout=300.0)
        extra.append(d3)

        class _P:
            def __init__(self, addr):
                self._i = PeerInfo(grpc_address=addr)

            def info(self):
                return self._i

        def owner_addr(hash_key, addrs):
            pick = ReplicatedConsistentHash(xx_64)
            for a in addrs:
                pick.add(_P(a))
            return pick.get(hash_key).info().grpc_address

        three = [d.grpc_address for d in cluster.daemons]
        four = three + [d3.grpc_address]
        movers = [
            k for k in keys
            if owner_addr(f"{TENANT}_{k}", four) == d3.grpc_address
        ]
        demoted = {
            owner_addr(f"{TENANT}_{k}", three): k for k in movers
        }

        cluster.daemons.append(d3)
        extra.remove(d3)
        cluster.run(cluster._push_peers(), timeout=60.0)
        # Outcome-based settle: every moved row becomes visible at its
        # new owner (TRANSFER -> CUTOVER landed its slots), and no
        # handoff is left half-open anywhere.  A started==completed
        # check alone would pass trivially BEFORE the first handoff
        # begins.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            moved_ok = all(
                _get(
                    http[0], f"/debug/key?name={TENANT}&key={k}"
                )["found"]
                for k in movers
            )
            settled = not d3.service.reshard._inbound and all(
                d.service.reshard.handoffs_started
                == d.service.reshard.handoffs_completed
                + d.service.reshard.handoffs_aborted
                for d in cluster.daemons
            )
            if moved_ok and settled:
                break
            time.sleep(0.1)
        else:
            raise AssertionError(
                f"reshard handoffs never settled: movers={movers} "
                f"ledgers={[d.service.reshard.debug_vars() for d in cluster.daemons]}"
            )

        # Every driven row still found exactly once via owner routing,
        # remaining bit-identical — no row lost, none double-applied.
        for k, pre in pre_rows.items():
            post = _get(http[0], f"/debug/key?name={TENANT}&key={k}")
            assert post["found"] is True, (k, post)
            assert post["row"]["remaining"] == pre["remaining"], (
                f"key {k}: remaining {post['row']['remaining']} "
                f"!= pre-join {pre['remaining']}"
            )
            assert post["row"]["created_at"] == pre["created_at"], k
        # The joiner's census shows the moved rows resident (poll: its
        # sampler needs a tick after the handoff completes)...
        if movers:
            deadline = time.monotonic() + 15.0
            while True:
                v3 = gubtop.scrape(d3.http_address)
                if v3.get("table", {}).get("live", 0) >= len(movers):
                    break
                if time.monotonic() > deadline:
                    raise AssertionError(
                        f"joiner census {v3.get('table')} misses "
                        f"{len(movers)} moved rows"
                    )
                time.sleep(0.1)
            # ...and the demoted owner no longer holds them.
            for old, k in demoted.items():
                d_old = next(
                    d for d in cluster.daemons
                    if d.grpc_address == old
                )
                gone = _get(
                    d_old.http_address,
                    f"/debug/key?name={TENANT}&key={k}&noproxy=1",
                )
                assert gone["found"] is False, (
                    f"demoted owner still holds {k}: {gone}"
                )

        print(
            f"stats smoke OK: seed={args.seed} "
            f"merged tenant {TENANT} allowed={allowed} denied={denied} "
            f"exactly, census live {total_live} across 3 nodes, "
            f"gubtop rendered {len(http)} nodes, /debug/key proxied + "
            f"bit-identical re-read, {len(movers)} rows conserved "
            f"across reshard join"
        )
    except BaseException:
        _dump_flightrec(cluster, extra, "stats-smoke-failure")
        raise
    finally:
        for d in extra:
            cluster.run(d.close(), timeout=60.0)
        cluster.stop()


if __name__ == "__main__":
    main()
