"""Trace smoke: ONE connected trace across a 2-daemon cluster, and a
trace-tagged breach dump — the ISSUE 7 acceptance run.

Three phases against real daemons (in-process cluster, ring serve mode,
flight recorder armed):

  0. DISABLED — tracing unconfigured: traffic flows, the span plane
     reports {"enabled": False}, zero spans exist, and flight-recorder
     records carry no trace ids (the hot path's default cost).
  1. ONE TRACE — a client root context rides w3c `traceparent` into
     daemon A, whose zero-copy forward carries it to the owner daemon
     B; the trace must contain: both daemons' `rpc.server` spans, the
     `peer.forward` hop, the owner's `fastpath.merge`, and a
     `ring.iteration` span carrying the monotone sequence-word
     attribute (`ring.seq`) — client -> coalescer merge -> ring round
     -> peer forward, one trace id end to end.
  2. BREACH DUMP — the owner daemon's SLO target is dropped to an
     unmeetable value; the forced dump's flightrec records carry the
     matching trace id AND the dump embeds the trace's spans
     (`traces` block), so the artifact CONTAINS the slow trace.

On failure every collected span is dumped to trace-smoke-dumps/ for
the CI artifact.  Runs in the CI matrix (JAX_PLATFORMS=cpu); exit 0 =
pass.
"""
from __future__ import annotations

import asyncio
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

DUMP_DIR = "trace-smoke-dumps"


def fail(msg: str, exporter=None) -> None:
    os.makedirs(DUMP_DIR, exist_ok=True)
    if exporter is not None:
        path = os.path.join(DUMP_DIR, "spans.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump(exporter.dicts(), f, indent=1)
        print(f"trace_smoke: spans dumped to {path}")
    print(f"trace_smoke: FAIL — {msg}")
    sys.exit(1)


def main() -> None:
    import grpc.aio

    from gubernator_tpu.core.config import DaemonConfig, DeviceConfig
    from gubernator_tpu.proto import gubernator_pb2 as pb
    from gubernator_tpu.runtime import tracing
    from gubernator_tpu.testing.cluster import Cluster
    from gubernator_tpu.testing.tracing import MemorySpanExporter

    conf = DaemonConfig(
        serve_mode="ring",
        ring_slots=4,
        flightrec=True,
        flightrec_dir=DUMP_DIR,
    )
    cluster = Cluster.start(
        2,
        device=DeviceConfig(num_slots=4096, ways=8, batch_size=128),
        conf_template=conf,
    )
    exporter = MemorySpanExporter()
    try:
        d0, d1 = cluster.daemon_at(0), cluster.daemon_at(1)
        # A key daemon 0 must FORWARD (owned by daemon 1).
        key = next(
            f"fwd{i}" for i in range(256)
            if cluster.owner_daemon_of(f"tsmoke_fwd{i}") is d1
        )
        payload = pb.GetRateLimitsReq(requests=[
            pb.RateLimitReq(
                name="tsmoke", unique_key=key, hits=1,
                limit=1000, duration=60_000,
            )
        ]).SerializeToString()

        async def call(metadata=None) -> None:
            ch = grpc.aio.insecure_channel(d0.grpc_address)
            try:
                rpc = ch.unary_unary("/pb.gubernator.V1/GetRateLimits")
                raw = await rpc(payload, metadata=metadata)
                resp = pb.GetRateLimitsResp.FromString(raw)
                if resp.responses[0].error:
                    raise RuntimeError(resp.responses[0].error)
            finally:
                await ch.close()

        # -- phase 0: disabled ------------------------------------------
        if tracing.enabled():
            fail("tracing unexpectedly enabled at start")
        for _ in range(5):
            cluster.run(call())
        if tracing.debug_vars() != {"enabled": False}:
            fail(f"disabled debug_vars: {tracing.debug_vars()}")
        for d in (d0, d1):
            tagged = [
                r for r in d.flightrec.snapshot()["ring"]
                if "trace_id" in r
            ]
            if tagged:
                fail(f"disabled run produced trace-tagged records: {tagged}")
        print("trace_smoke: phase 0 OK — 0 spans while disabled")

        # -- phase 1: one connected trace -------------------------------
        status = tracing.init_tracing(exporter=exporter)
        if not status.enabled:
            fail(f"init_tracing refused: {status.reason}")
        client_ctx = tracing.SpanContext(
            tracing._new_trace_id(), tracing._new_span_id(), True
        )
        cluster.run(call(
            metadata=(("traceparent", client_ctx.traceparent()),)
        ))
        tid = client_ctx.trace_id_hex()
        spans = exporter.spans_for_trace(tid)
        names = sorted({s.name for s in spans})
        methods = {
            s.attributes.get("rpc.method")
            for s in spans if s.name == "rpc.server"
        }
        if "/pb.gubernator.V1/GetRateLimits" not in methods:
            fail(f"daemon A server span missing (got {names})", exporter)
        if "/pb.gubernator.PeersV1/GetPeerRateLimits" not in methods:
            fail(f"peer server span missing (got {names})", exporter)
        if not any(s.name == "peer.forward" for s in spans):
            fail(f"peer.forward span missing (got {names})", exporter)
        if not any(s.name == "fastpath.merge" for s in spans):
            fail(f"fastpath.merge span missing (got {names})", exporter)
        its = [s for s in spans if s.name == "ring.iteration"]
        if not its or "ring.seq" not in its[0].attributes:
            fail(
                f"ring.iteration with ring.seq missing (got {names})",
                exporter,
            )
        print(
            "trace_smoke: phase 1 OK — one trace "
            f"({len(spans)} spans: {names}), ring.seq="
            f"{its[0].attributes['ring.seq']}"
        )

        # -- phase 2: trace-tagged breach dump --------------------------
        fr = d1.flightrec
        fr.slo_p99_ms = 1e-6  # unmeetable: the next window breaches
        fr.min_samples = 1
        reason = fr.evaluate()
        if reason != "slo_breach":
            fail(f"expected slo_breach, got {reason!r}", exporter)
        path = cluster.run(fr.dump(reason))
        with open(path, encoding="utf-8") as f:
            dump = json.load(f)
        ring_tids = {
            r.get("trace_id") for r in dump["ring"] if "trace_id" in r
        }
        if tid not in ring_tids:
            fail(
                f"breach dump ring records missing trace {tid} "
                f"(have {ring_tids})", exporter,
            )
        dump_traces = {s["trace_id"] for s in dump.get("traces", [])}
        if tid not in dump_traces:
            fail(
                f"breach dump embeds no spans of trace {tid}", exporter
            )
        dumped_names = {
            s["name"] for s in dump["traces"] if s["trace_id"] == tid
        }
        print(
            "trace_smoke: phase 2 OK — breach dump at "
            f"{os.path.basename(path)} carries trace {tid[:8]}… "
            f"({sorted(dumped_names)})"
        )
    finally:
        from gubernator_tpu.runtime.tracing import shutdown_tracing

        shutdown_tracing()
        cluster.stop()
    print("trace_smoke: PASS")


if __name__ == "__main__":
    main()
