"""Offline multi-seed differential sweeps — deeper than the CI seeds.

Usage:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/sweep_differentials.py mixed 0 20
    python scripts/sweep_differentials.py store 0 15
    python scripts/sweep_differentials.py routed        # all hashes x seeds
    python scripts/sweep_differentials.py mesh          # extra seeds

`mixed` and `store` replay the in-repo fuzz differentials with arbitrary
seed ranges; `routed`/`mesh` re-run the wire differentials with a
seed-overriding random.Random so the fixed in-test streams vary.  Run
before shipping any change to runtime/fastpath.py, ops/step.py response
semantics, or the GLOBAL managers (see tests/test_fastpath.py for the
tiers these deepen).
"""
from __future__ import annotations

import os
import random as _random
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    )

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

import conftest  # noqa: E402,F401 — pins the CPU platform pre-jax

from gubernator_tpu.core import clock as clock_mod  # noqa: E402

_orig_random = _random.Random


class _SeededRandom(_orig_random):
    seed_override = None

    def __init__(self, seed=None):
        super().__init__(
            self.seed_override if self.seed_override is not None else seed
        )


def _with_seed(seed, fn, *args):
    _SeededRandom.seed_override = seed
    _random.Random = _SeededRandom
    clock_mod.freeze()
    try:
        fn(clock_mod.default_clock(), *args)
    finally:
        clock_mod.unfreeze()
        _random.Random = _orig_random


def main() -> None:
    import test_fastpath as tf

    which = sys.argv[1] if len(sys.argv) > 1 else "mixed"
    lo = int(sys.argv[2]) if len(sys.argv) > 2 else 0
    # Default depth when no explicit hi: 10 seeds (2 for the slow wire
    # sweeps).  An explicit hi is honored exactly — never widened.
    if len(sys.argv) > 3:
        hi = int(sys.argv[3])
    elif which in ("routed", "mesh"):
        hi = lo + 2
    else:
        hi = lo + 10
    if which == "mixed":
        for s in range(lo, hi):
            clock_mod.freeze()
            try:
                tf.test_fastpath_differential_mixed_behaviors(
                    clock_mod.default_clock(), s
                )
            finally:
                clock_mod.unfreeze()
            print(f"mixed seed {s} ok", flush=True)
    elif which == "store":
        for s in range(lo, hi):
            _with_seed(s, tf.test_fastpath_store_differential)
            print(f"store seed {s} ok", flush=True)
    elif which == "routed":
        for ph in ("xx", "fnv1", "fnv1a"):
            for s in range(lo, hi):
                _with_seed(
                    s, tf.test_multinode_routed_wire_differential, ph
                )
                print(f"routed {ph} seed {s} ok", flush=True)
    elif which == "mesh":
        for s in range(lo, hi):
            _with_seed(s, tf.test_mesh_cluster_wire_differential)
            print(f"mesh seed {s} ok", flush=True)
    else:
        raise SystemExit(f"unknown sweep {which!r}")


if __name__ == "__main__":
    main()
