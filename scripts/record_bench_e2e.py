"""Run bench_e2e on the rig and assemble BENCH_E2E_r{N}.json.

Usage: python scripts/record_bench_e2e.py [seconds] [concurrency] [round]
                                          [suffix] [workload] [mesh_shards]
                                          [client_modes]

A non-empty `suffix` names a variant artifact (BENCH_E2E_r{N}_{suffix}
.json) for A/B runs; the GUBER_FASTPATH_SPARSE env var passes through to
bench_e2e's cluster configs.  `workload` (e.g. zipf:1.2) adds the
skewed-key owner-share config (bench_e2e --workload; docs/hotkeys.md).
`mesh_shards` (e.g. 8) adds the mesh deployment-mode serve sweep with
per-shard occupancy (bench_e2e --mesh-shards; docs/architecture.md).
"""
import json
import os
import subprocess
import sys

SECONDS = sys.argv[1] if len(sys.argv) > 1 else "5"
CONC = sys.argv[2] if len(sys.argv) > 2 else "16"
ROUND = int(sys.argv[3]) if len(sys.argv) > 3 else 7
SUFFIX = sys.argv[4] if len(sys.argv) > 4 else ""
WORKLOAD = sys.argv[5] if len(sys.argv) > 5 else "zipf:1.2"
MESH_SHARDS = sys.argv[6] if len(sys.argv) > 6 else "0"
CLIENT_MODES = (
    sys.argv[7] if len(sys.argv) > 7 else "python,native,leased"
)

try:
    cmd = [sys.executable, "/root/repo/bench_e2e.py", "--seconds",
           SECONDS, "--concurrency", CONC]
    if WORKLOAD:
        cmd += ["--workload", WORKLOAD]
    if MESH_SHARDS not in ("", "0"):
        cmd += ["--mesh-shards", MESH_SHARDS]
    cmd += ["--client-mode", CLIENT_MODES]
    out = subprocess.run(
        cmd,
        capture_output=True, text=True, timeout=1800,
    )
    stdout = out.stdout
except subprocess.TimeoutExpired as e:
    # A dark device tunnel hangs bench_e2e rather than erroring; keep
    # whatever configs completed before the budget (partial artifact
    # with the timeout labeled) instead of crashing with no artifact.
    out = None
    stdout = (e.stdout or b"").decode() if isinstance(
        e.stdout, bytes) else (e.stdout or "")
    stdout += (
        '\n{"config": "recorder_timeout", "error": '
        '"bench_e2e exceeded 1800s (device tunnel dark?)"}'
    )
results = []
for line in stdout.splitlines():
    line = line.strip()
    if line.startswith("{"):
        try:
            results.append(json.loads(line))
        except json.JSONDecodeError:
            pass
if not results:
    sys.stderr.write(
        stdout[-2000:] + "\n" + (out.stderr[-4000:] if out else "") + "\n"
    )
    raise SystemExit("no results parsed")

# Platform honesty: take the ACTUAL jax platform from bench_e2e's own
# summary line — a CPU run must never masquerade as the TPU rig.
_summary_platform = next(
    (r.get("platform") for r in results if r.get("config") == "summary"),
    None,
)
artifact = {
    "round": ROUND,
    "harness": (
        f"bench_e2e.py --seconds {SECONDS} --concurrency {CONC}"
        + (f" --workload {WORKLOAD}" if WORKLOAD else "")
        + (
            f" --mesh-shards {MESH_SHARDS}"
            if MESH_SHARDS not in ("", "0") else ""
        )
        + (f" --client-mode {CLIENT_MODES}" if CLIENT_MODES else "")
    ),
    "platform": (
        "tpu (single chip via axon tunnel)"
        if _summary_platform == "tpu" else (_summary_platform or "unknown")
    ),
    "note": (
        "E2E daemon service path: gRPC wire -> compiled fast lane (C++ "
        "parse/pack/serialize) -> device step -> wire.  The rig's cost "
        "unit is the HOST FETCH (~70-300ms per device->host read); its "
        "dispatch additionally degrades to ~one RTT per step after a "
        "process's first fetch (sticky sync mode), which co-location "
        "removes.  Round-5 changes measured here: (1) GLOBAL broadcast "
        "rows are captured from each owning drain's own post-step stored "
        "columns (new stored_status kernel output) — the zero-hit "
        "re-read steps of global.go:205-250 run only as a degradation "
        "fallback, so reread_batches is 0 in steady state and the GLOBAL "
        "lane sheds its per-window object-path device cycles; (2) store "
        "drains drop the pre-step residency probe (the step's own "
        "`found` column gates Store.get; cold keys repair in place), so "
        "a warm store drain pays ONE combined response+capture fetch — "
        "storeless parity; (3) the sparse-overlap default (64, 3 slots) "
        "was re-A/B'd interleaved: small-batch p50 156->86ms in both "
        "reps, token throughput inside run-to-run noise — README, "
        "config, and this artifact now tell one story; (4) the "
        "co-located latency bound separates the python grpc.aio client's "
        "own machinery (~1.3ms p50 of the wire loopback) from the "
        "server-side handler path (~30us p50), measures device "
        "execution in a fetch-free subprocess, and reports the bare "
        "grpc.aio byte-echo floor under the loopback (grpc_aio_floor_*, "
        "same payload, same drive() harness: loopback median minus floor "
        "median = the framework's own wire overhead).  The GLOBAL accounting "
        "also reports the shared-chip normalization: all 4 daemons of "
        "the global_4peer cluster run against this rig's ONE device, so "
        "the measured global/exact ratio includes cross-daemon device-"
        "queue interleave that a chip-per-daemon deployment does not "
        "pay.  Tunnel throughput varies +-30% run to run.  Round-6 "
        "addition: the serve_sweep_* configs A/B the three drain "
        "disciplines (GUBER_SERVE_MODE=classic|pipelined|ring; "
        "docs/ring.md) and the budget/serve_sweep_stages lines carry "
        "blocking_fetches_per_check — the ring acceptance criterion is "
        "that ring mode's steady-state blocking device->host fetches on "
        "the request path are ZERO (readbacks move to the ring runner) "
        "with small-batch p50 at or below the pipelined baseline.  "
        "Round-7 addition: the zipf_owner_skew_s<sigma> config "
        "(--workload zipf:<s>) drives seeded zipfian key draws at a "
        "3-daemon cluster and reports the per-owner share of applied "
        "checks next to p50/p99 — the single-owner funnel the hot-key "
        "survival plane (docs/hotkeys.md) exists to survive; its "
        "mirroring stays provably inactive here because no owner "
        "breaches its SLO.  Round-8 addition: the mesh_serve_sweep_* "
        "configs (--mesh-shards N) re-run the serve-mode A/B on an "
        "N-shard MESH daemon — the deployment-mode benchmark "
        "(docs/architecture.md): mesh ring mode must hold "
        "blocking_fetches_per_check == 0 (engine lane included; GLOBAL "
        "readbacks and psum syncs ride the ring runner), and the "
        "mesh_serve_sweep_stages line reports per-shard occupancy, "
        "per-shard ring sequence words, and the ring slot-wait budget "
        "term.  On a CPU rig the N virtual devices share one host, so "
        "mesh absolute throughput is NOT comparable to the single-"
        "device configs — the claims this artifact supports there are "
        "the zero-fetch discipline and the per-shard accounting, not a "
        "speedup.  Round-9 addition: the client_sweep_* configs "
        "(--client-mode python,native,leased) drive the SAME steady "
        "single-key load through each SDK tier measuring the CLIENT's "
        "own machinery (the other configs pre-serialize payloads to "
        "exclude it): V1Client (python protobuf per call), FastV1Client "
        "(the compiled request-serialize/response-unmarshal codec, "
        "native/gubtpu.cpp gub_serialize_reqs + gub_parse_resps2 over a "
        "raw-bytes channel), and LeasedClient (client-side admission, "
        "docs/leases.md: checks burn an owner-granted local allowance "
        "with ZERO RPCs, reconciled asynchronously).  The acceptance "
        "column is rpcs_per_admitted_check in client_mode_budget — the "
        "leased client must sit >= 10x below the python client under "
        "steady single-key load.  On a CPU rig the native codec's "
        "per-RPC win is masked by the ~3ms server round trip (its "
        "~1.3ms saving is the CO-LOCATED claim, where the round trip "
        "is sub-ms); the leased ratio is rig-independent because its "
        "checks never leave the process."
    ),
    "results": results,
}
if SUFFIX:
    artifact["variant"] = SUFFIX
if "GUBER_FASTPATH_SPARSE" in os.environ:
    # Record the override wherever it was applied — a suffix-less run
    # with the knob set must not masquerade as a default-config artifact.
    artifact["harness"] += "  [env GUBER_FASTPATH_SPARSE=%s]" % (
        os.environ["GUBER_FASTPATH_SPARSE"],
    )
out_path = "/root/repo/BENCH_E2E_r%02d%s.json" % (
    ROUND, ("_" + SUFFIX) if SUFFIX else "",
)
with open(out_path, "w") as f:
    json.dump(artifact, f, indent=1)
print("wrote", out_path, "with", len(results), "results")
for r in results:
    if "checks_per_sec" in r:
        print(r["config"], r["checks_per_sec"])
    if r.get("config") == "colocated_latency_bound":
        print("bound:", {k: v for k, v in r.items()
                         if k.startswith("implied") or "p99" in k})
