"""CI smoke: run the gubrange plane end-to-end the way an operator
does — the CLI over the real registry must pass strict-clean (every
kernel carries an envelope, zero unbounded intermediates, zero unit
errors, every expect_peak exact), and the shipped negative-control
fixture (unclamped hits*cost) must fail with an overflow finding whose
witness is a REAL kernel execution showing the wrapped output.

Run from the repo root:  python scripts/gubrange_smoke.py
Exits non-zero with a labeled assertion on any missing piece.
(Mirrors scripts/gubtrace_smoke.py / scripts/gubproof_smoke.py.)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Runnable from a checkout without an installed package.
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    # 1. The CLI over the real registry passes strict-clean: both
    #    phases (interval ranges + host suffix discipline), warnings
    #    fatal.
    proc = subprocess.run(
        [sys.executable, "-m", "tools.gubrange", "--json", "--strict"],
        cwd=REPO, capture_output=True, text=True, timeout=900,
        env={**os.environ},
    )
    assert proc.returncode == 0, (
        f"gubrange CLI failed (rc={proc.returncode}):\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    assert json.loads(proc.stdout) == [], (
        f"tree not clean: {proc.stdout}"
    )

    # 2. Envelope coverage is total: every registered kernel analyzed
    #    (the CLI already errors on a missing or stale envelope; this
    #    pins the expected kernel count so silent registry shrinkage
    #    can't fake a pass).
    from tools.gubrange.envelope import load_envelopes
    from tools.gubtrace.registry import specs

    names = {s.name for s in specs()}
    envs = set(load_envelopes())
    assert len(names) >= 28, f"registry shrank to {len(names)} kernels"
    assert envs == names, (
        f"envelope/registry drift: only-envelope={sorted(envs - names)} "
        f"only-registry={sorted(names - envs)}"
    )

    # 3. The negative control: the shipped unclamped hits*cost fixture
    #    must produce an overflow finding AND an executed witness whose
    #    output is the exact two's-complement wrap.
    from pathlib import Path

    from tools.gubrange import run
    from tools.gubrange.fixture import fixture_specs

    fs = run(
        select=["ranges"], specs=fixture_specs(),
        envelope_dir=Path(REPO) / "tests/gubrange_fixtures/envelopes",
        root=Path(REPO),
    )
    overflow = [f for f in fs if f.checker == "overflow"]
    assert overflow, (
        "negative-control fixture did not overflow: "
        + "\n".join(f.render() for f in fs)
    )
    witness = [f for f in fs if f.checker == "witness"]
    assert witness, "overflow finding shipped no executed witness"
    wrapped = str((4_000_000_000 * 4_000_000_000) % 2**64 - 2**64)
    assert "WRAPPED" in witness[0].message, witness[0].message
    assert wrapped in witness[0].message, (
        f"witness does not show the concrete wrap {wrapped}: "
        f"{witness[0].message}"
    )
    print(f"gubrange smoke: negative control wrapped to {wrapped}")

    print("gubrange smoke: PASS")


if __name__ == "__main__":
    main()
