"""CI smoke: seeded chaos scenarios against a 3-daemon in-process
cluster (the compressed version of tests/test_chaos.py +
tests/test_hotkey.py).

Scenarios (--scenario storm|hotkey|lease|reshard|coldstorm|
regionsplit|all; default storm — the original job; CI runs hotkey,
lease, reshard, coldstorm and regionsplit as their own required
steps):

  storm   a seeded storm of client/server faults (>=30% of peer RPCs
          fail) with breakers + `local_shadow` degraded mode armed:
          zero double counts, at least one breaker trips, every breaker
          re-closes after heal.

  hotkey  a seeded ZIPFIAN storm that overloads ONE owner
          (docs/hotkeys.md): server-side delay injection drives the
          owner's measured p99 through its SLO; the smoke then asserts
          the hot-key survival plane end to end — mirroring provably
          inactive before pressure, total admitted hits for the hot
          key within limit x (1 + mirrors x fraction) during the
          storm, shedding priority-ordered on the pressured owner (a
          sheddable class drops with retry-after while an unmatched
          class serves), and after the skew clears the hot-set demotes
          to empty with the widening fully collapsed.

  lease   the client-side admission bound under partition
          (docs/leases.md): a LeasedClient holding a grant is cut off
          from the key's owner; it burns EXACTLY its remaining
          allowance with zero RPCs and never one hit more, direct
          traffic saturates the authoritative row, and total admission
          lands exactly on limit x (1 + holders x fraction).  After
          heal, a fresh key proves burned hits reconcile into the
          owner's row exactly once (queue_hit at-most-once through the
          proxy daemon), and the owner re-collects: released grants
          drop the carve slot.

  reshard membership churn mid-traffic (docs/resharding.md): a JOIN
          whose Migrate chunks are 100% chaos-failed holds the handoff
          window open — a fully consumed key admits EXACTLY
          handoff_fraction x limit more through the new owner's shadow
          (admitted == limit x (1 + fraction), never one hit over);
          after heal the transfer completes, post-cutover reads at the
          new owner bit-match the pymodel continuation (remaining/t0/
          reset preserved), the old owner's slots are purged (no daemon
          serves from an orphaned slot), and a graceful LEAVE drains
          every row back to the survivors with counters conserved.

  coldstorm the Guberberg two-tier table under an 8x-slots keyspace
          (docs/tiering.md): one tier-enabled daemon with 1024 HBM
          slots serves 8192 keys; the watermark loop demotes, zipfian
          reuse drives cold hits + promote-on-access, and the merged
          /debug/vars ledger proves admission within the documented
          bound (allowed <= limit x (keys + demote cycles)).  Then
          kill + restart: the checkpoint restores BOTH tiers (cold
          residents + HBM occupancy conserved) and an exhausted key
          stays denied — no limit reset.

  regionsplit a two-region active-active cluster cut in half
          (docs/multiregion.md): a west-homed key keeps serving from
          east's bounded `.region-carve` slot while the WAN is severed
          — east admits EXACTLY fraction x limit, west saturates the
          authoritative row, total admission lands exactly on
          limit x (1 + regions x fraction) with the merged /debug/vars
          ledger showing region-carve over-admission == the carve.
          After heal the burn backlog reconciles at-most-once into the
          (saturated) home row, drift reconverges to zero, the link
          re-homes through REGION_PREPARE -> TRANSFER -> CUTOVER, and
          the carve keeps its consumed state (no per-heal refresh).

On any failure each daemon's flight recorder dumps its ring to
GUBER_FLIGHTREC_DIR (default flightrec-dumps/) so the CI artifact step
can pick the evidence up.

Run from the repo root:  python scripts/chaos_smoke.py [--seed N]
The whole run is deterministic given the seed (docs/resilience.md).
"""
from __future__ import annotations

import argparse
import os
import random
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Runnable from a checkout without an installed package.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LIMIT = 1000
DURATION = 60_000
KEYS = 20
ROUNDS = 5


def _dump_flightrec(cluster, reason: str) -> None:
    for d in cluster.daemons:
        if d.flightrec is not None:
            path = cluster.run(d.flightrec.dump(reason))
            print(f"flightrec dump ({d.grpc_address}): {path}")


def _merged_tenant(daemons, name: str) -> dict:
    """The cluster-wide per-tenant ledger, merged from LIVE /debug/vars
    scrapes with gubtop's own merge (docs/observability.md) — the
    production metrics surface, never test internals.  Local-serve
    counting makes the sum exact, so the paper's over-admission bounds
    are asserted against what an operator actually sees."""
    from gubernator_tpu.cli import gubtop

    scrapes = {d.http_address: gubtop.scrape(d.http_address)
               for d in daemons}
    for t in gubtop._merge_tenants(scrapes, 64):
        if t["name"] == name:
            return t
    raise AssertionError(
        f"tenant {name!r} missing from merged /debug/vars ledgers: "
        f"{[v.get('tenants') for v in scrapes.values()]}"
    )


def storm_scenario(seed: int) -> None:
    from gubernator_tpu.client import V1Client
    from gubernator_tpu.core.config import CircuitConfig, DaemonConfig
    from gubernator_tpu.core.types import RateLimitReq
    from gubernator_tpu.testing import (
        ChaosInjector,
        ChaosPlan,
        Cluster,
        Rule,
    )
    args = argparse.Namespace(seed=seed)

    injector = ChaosInjector(ChaosPlan(seed=args.seed))
    injector.set_active(False)  # boot/peer-discovery runs clean
    cluster = Cluster.start_with(
        ["", "", ""],
        conf_template=DaemonConfig(
            # Fast breaker schedule so open -> half-open -> re-close
            # cycles fit a smoke budget.
            circuit=CircuitConfig(
                failure_threshold=3, base_backoff_s=0.1,
                max_backoff_s=1.0, jitter=0.2,
            ),
            degraded_mode="local_shadow",
            shadow_fraction=0.25,
            chaos=injector,
            flightrec=True,
            flightrec_dir=os.environ.get(
                "GUBER_FLIGHTREC_DIR", "flightrec-dumps"
            ),
        ),
    )

    try:
        # The same fault mix as test_seeded_plan_no_double_count, with
        # the hard-failure rates bumped so the >=30% floor holds at
        # smoke sample sizes: unsent client errors (retry-safe),
        # pre-apply server rejections, drops and delays.
        injector.reset(ChaosPlan(seed=args.seed, rules=[
            Rule(op="error", where="client", method="GetPeerRateLimits",
                 probability=0.28, status="UNAVAILABLE",
                 message="injected: failed to connect to all addresses"),
            Rule(op="error", where="server", phase="before",
                 method="GetPeerRateLimits", probability=0.15,
                 status="UNAVAILABLE",
                 message="injected: refused before apply"),
            Rule(op="drop", where="client", method="GetPeerRateLimits",
                 probability=0.04, delay_s=0.01),
            Rule(op="delay", where="client", method="GetPeerRateLimits",
                 probability=0.10, delay_s=0.005),
        ]))

        keys = [f"smoke{i}" for i in range(KEYS)]
        ok = {k: 0 for k in keys}
        cl = V1Client(cluster.addresses()[0])
        try:
            for _round in range(ROUNDS):
                for k in keys:
                    r = cl.get_rate_limits([
                        RateLimitReq(
                            name="chaos", unique_key=k, hits=1,
                            limit=LIMIT, duration=DURATION,
                        )
                    ], timeout=30)[0]
                    if r.error == "" and "degraded" not in (r.metadata or {}):
                        ok[k] += 1
        finally:
            cl.close()

        frac = injector.failure_fraction()
        assert frac >= 0.30, (
            f"storm too mild: {frac:.0%} injected failures "
            f"({dict(injector.injected)})"
        )

        forwarded = 0
        for k in keys:
            hash_key = f"chaos_{k}"
            owner = cluster.owner_daemon_of(hash_key)
            if owner is not cluster.daemons[0]:
                forwarded += 1
            it = owner.service.backend.get_cache_item(hash_key)
            applied = 0 if it is None else LIMIT - int(it.remaining)
            assert applied == ok[k], (
                f"key {k}: owner applied {applied}, client saw "
                f"{ok[k]} successes — double count or lost hit"
            )
        assert forwarded >= 5, f"only {forwarded} keys forwarded"

        trips = sum(
            p.breaker.trips
            for d in cluster.daemons
            for p in d.service.peer_list()
            if p.breaker is not None and not p.info().is_owner
        )
        assert trips >= 1, "no breaker tripped during the storm"

        # Heal; probe from every daemon until every breaker re-closes.
        injector.heal()
        clients = [V1Client(a) for a in cluster.addresses()]
        try:
            deadline = time.monotonic() + 20.0
            while True:
                for c2 in clients:
                    c2.get_rate_limits([
                        RateLimitReq(
                            name="quiesce",
                            unique_key=f"q{random.random()}",
                            hits=1, limit=LIMIT, duration=DURATION,
                        )
                        for _ in range(4)
                    ], timeout=30)
                states = cluster.breaker_states()
                stuck = [
                    (a, pa, s)
                    for a, peers in states.items()
                    for pa, s in peers.items()
                    if s not in ("closed", "disabled")
                ]
                if not stuck:
                    break
                if time.monotonic() > deadline:
                    raise AssertionError(
                        f"breakers never re-closed after heal: {stuck}"
                    )
                time.sleep(0.1)
        finally:
            for c2 in clients:
                c2.close()

        print(
            f"chaos smoke OK: seed={args.seed} "
            f"injected={frac:.0%} of {injector.attempts['client']} "
            f"client RPCs, trips={trips}, forwarded_keys={forwarded}, "
            f"all breakers re-closed"
        )
    except BaseException:
        _dump_flightrec(cluster, "chaos-smoke-failure")
        raise
    finally:
        cluster.stop()


def hotkey_scenario(seed: int) -> None:
    """The zipfian single-owner overload (docs/hotkeys.md lifecycle)."""
    from gubernator_tpu.client import V1Client
    from gubernator_tpu.core.config import DaemonConfig, HotKeyConfig
    from gubernator_tpu.core.types import RateLimitReq, Status
    from gubernator_tpu.testing import (
        ChaosInjector,
        ChaosPlan,
        Cluster,
        Rule,
        zipf_keys,
    )

    hot_limit = 200
    mirrors, fraction = 1, 0.25
    injector = ChaosInjector(ChaosPlan(seed=seed))
    injector.set_active(False)  # boot runs clean
    cluster = Cluster.start_with(
        ["", "", ""],
        conf_template=DaemonConfig(
            hotkey=HotKeyConfig(
                threshold=50.0, mirrors=mirrors, fraction=fraction,
                window_s=0.3, promote_windows=2, demote_windows=2,
                pressure_ttl_s=1.5, shed_cooldown_s=0.4,
                shed_priorities=["bulk.*"],
            ),
            chaos=injector,
            flightrec=True,
            flightrec_dir=os.environ.get(
                "GUBER_FLIGHTREC_DIR", "flightrec-dumps"
            ),
        ),
    )
    try:
        for d in cluster.daemons:
            # Shorten the rolling window so pressure clears within the
            # smoke budget after the skew stops; keep the production
            # 2ms target — the injected delay breaches it organically.
            d.flightrec.window_s = 2.0
            d.flightrec.slo_p99_ms = 2.0

        d0 = cluster.daemons[0]
        # A hot key owned by ANOTHER daemon whose first next-arc mirror
        # is d0 — deterministic from the shared ring.
        hot_key = next(
            f"h{i}" for i in range(2000)
            if not d0.service.local_picker.get_n(
                f"hot_h{i}", 2)[0].info().is_owner
            and d0.service.local_picker.get_n(
                f"hot_h{i}", 2)[1].info().is_owner
        )
        hash_key = f"hot_{hot_key}"
        owner = cluster.owner_daemon_of(hash_key)
        owner_addr = owner.grpc_address
        # Zipfian tail around the hot head: seeded background draws.
        tail = zipf_keys(seed, 1.3, 4000, 500)

        cl = V1Client(d0.grpc_address)
        admitted = 0
        mirror_meta = 0

        def storm_round(n_hot: int, round_idx: int):
            nonlocal admitted, mirror_meta
            reqs = [
                RateLimitReq(name="hot", unique_key=hot_key, hits=1,
                             limit=hot_limit, duration=DURATION)
                for _ in range(n_hot)
            ] + [
                RateLimitReq(name="hot", unique_key=f"t{t}", hits=1,
                             limit=LIMIT, duration=DURATION)
                for t in tail[round_idx * 20:(round_idx + 1) * 20]
            ]
            for r, req in zip(cl.get_rate_limits(reqs, timeout=30),
                              reqs):
                if req.unique_key != hot_key:
                    continue
                if r.error == "" and r.status == Status.UNDER_LIMIT:
                    admitted += 1
                if (r.metadata or {}).get("hotkey") == "mirror":
                    mirror_meta += 1

        try:
            # Phase 0 — skewed traffic, NO owner pressure: mirroring
            # must be provably inactive.
            for i in range(4):
                storm_round(30, i)
                time.sleep(0.1)
            assert d0.service.mirror_served == 0, (
                "mirroring active without measured owner pressure"
            )
            assert len(d0.service.active_mirror_fps()) == 0

            # Phase 1 — overload the owner: every peer RPC it serves
            # gains an injected 25ms server-side delay, so its MEASURED
            # p99 breaches the 2ms SLO while it stays fully alive.
            injector.reset(ChaosPlan(seed=seed, rules=[
                Rule(op="delay", where="server", phase="before",
                     target=owner_addr, method="GetPeerRateLimits",
                     probability=1.0, delay_s=0.025),
            ]))
            deadline = time.monotonic() + 30.0
            i = 4
            while time.monotonic() < deadline:
                storm_round(50, i % 100)
                i += 1
                if mirror_meta > 0:
                    break
            assert mirror_meta > 0, "mirroring never activated"
            owner_peer = d0.service.get_peer(hash_key)
            assert owner_peer.pressure_ratio() >= 1.0, (
                "owner pressure never advertised"
            )
            assert owner_peer.circuit_state_name() in (
                "closed", "disabled"
            ), "breaker tripped — the owner must be alive, only slow"

            # Saturate both allowances, then check the proven bound.
            for _ in range(8):
                storm_round(60, i % 100)
                i += 1
            bound = hot_limit * (1 + mirrors * fraction)
            assert admitted <= bound, (
                f"over-admission: {admitted} > {bound}"
            )
            assert admitted >= hot_limit * 0.75, (
                f"storm never saturated the key ({admitted})"
            )
            # The same bound, reproduced from the LIVE metrics surface
            # (docs/observability.md): every mirror admission is a
            # client-visible UNDER_LIMIT, so the merged ledger's
            # hot-mirror over-admission is positive (mirroring really
            # served), never exceeds the admissions the client saw,
            # and accounts for every admission past the base limit.
            # (The cumulative counter can pass fraction x limit across
            # demote/re-promote cycles — the per-window carve bound is
            # what `admitted <= bound` above pins.)
            over = _merged_tenant(cluster.daemons, "hot")[
                "over_admitted"
            ].get("hot-mirror", 0)
            assert 0 < over <= admitted, (
                f"live hot-mirror over-admission {over} outside "
                f"(0, admitted {admitted}]"
            )
            assert admitted <= hot_limit + over, (
                f"admitted {admitted} > limit {hot_limit} + live "
                f"over-admission {over}"
            )

            # Priority-ordered shedding on the pressured owner: the
            # sheddable class drops with retry-after, the unmatched
            # class serves.
            cl_o = V1Client(owner_addr)
            try:
                def shed_seen():
                    rs = cl_o.get_rate_limits([
                        RateLimitReq(name="bulk.jobs", unique_key="b",
                                     hits=1, limit=LIMIT,
                                     duration=DURATION),
                        RateLimitReq(name="keep", unique_key="kp",
                                     hits=1, limit=LIMIT,
                                     duration=DURATION),
                    ], timeout=30)
                    assert (rs[0].metadata or {}).get("shed") == \
                        "pressure", rs[0]
                    assert int(rs[0].metadata["retry_after_ms"]) > 0
                    assert (rs[1].metadata or {}).get("shed") is None, (
                        "unmatched-priority name was shed"
                    )
                    return rs

                shed_deadline = time.monotonic() + 15.0
                while True:
                    try:
                        shed_seen()
                        break
                    except AssertionError:
                        if time.monotonic() > shed_deadline:
                            raise
                        storm_round(20, i % 100)
                        i += 1
                        time.sleep(0.1)
            finally:
                cl_o.close()
            shed_total = owner.service.shed_served
            # Shedding is tenant-attributed on the live surface too:
            # the shed class shows shed hits, the kept class none.
            assert _merged_tenant(
                cluster.daemons, "bulk.jobs"
            )["shed"] >= 1, "live ledger missed the shed tenant"
            assert _merged_tenant(
                cluster.daemons, "keep"
            )["shed"] == 0, "unmatched-priority tenant counted as shed"

            # Phase 2 — the skew clears: pressure drains out of the
            # rolling window, the hot-set demotes to empty, and the
            # widening fully collapses.
            injector.heal()
            collapse_deadline = time.monotonic() + 30.0
            while time.monotonic() < collapse_deadline:
                cl.get_rate_limits([
                    RateLimitReq(name="probe", unique_key="p", hits=1,
                                 limit=LIMIT, duration=DURATION)
                ], timeout=30)  # keep detection windows rolling
                if (not d0.service.hotkeys.hot_set
                        and len(d0.service.active_mirror_fps()) == 0):
                    break
                time.sleep(0.2)
            assert not d0.service.hotkeys.hot_set, (
                "hot-set never demoted after the skew cleared"
            )
            assert len(d0.service.active_mirror_fps()) == 0
            print(
                f"hotkey smoke OK: seed={seed} key={hash_key} "
                f"owner={owner_addr} admitted={admitted} "
                f"(bound {bound:g}), mirror_served="
                f"{d0.service.mirror_served}, owner_shed={shed_total}, "
                f"promotions={d0.service.hotkeys.promotions}, "
                f"demotions={d0.service.hotkeys.demotions}, "
                f"widening collapsed"
            )
        finally:
            cl.close()
    except BaseException:
        _dump_flightrec(cluster, "hotkey-smoke-failure")
        raise
    finally:
        cluster.stop()


def lease_scenario(seed: int) -> None:
    """The partitioned lease holder (docs/leases.md acceptance)."""
    from gubernator_tpu.client import LeasedClient, V1Client
    from gubernator_tpu.core.config import (
        CircuitConfig,
        DaemonConfig,
        LeaseConfig,
    )
    from gubernator_tpu.core.types import RateLimitReq, Status
    from gubernator_tpu.runtime.lease import LEASE_SUFFIX
    from gubernator_tpu.testing import ChaosInjector, ChaosPlan, Cluster

    limit = 200
    fraction, holders = 0.25, 1
    allowance = int(limit * fraction)  # 50
    lease_cfg = LeaseConfig(
        fraction=fraction, ttl_ms=60_000, max_holders=holders,
        reconcile_ms=300, low_water=0.0,
    )
    injector = ChaosInjector(ChaosPlan(seed=seed))
    injector.set_active(False)  # boot runs clean
    cluster = Cluster.start_with(
        ["", "", ""],
        conf_template=DaemonConfig(
            lease=lease_cfg,
            # Fast breaker so post-heal half-open probes fit the budget.
            circuit=CircuitConfig(
                failure_threshold=3, base_backoff_s=0.1,
                max_backoff_s=1.0, jitter=0.2,
            ),
            chaos=injector,
            flightrec=True,
            flightrec_dir=os.environ.get(
                "GUBER_FLIGHTREC_DIR", "flightrec-dumps"
            ),
        ),
    )
    try:
        d0 = cluster.daemons[0]
        # A key owned by another daemon — d0 is the holder's proxy.
        key = next(
            f"L{i}" for i in range(1000)
            if not d0.service.get_peer(f"lease_L{i}").info().is_owner
        )
        hash_key = f"lease_{key}"
        owner = cluster.owner_daemon_of(hash_key)
        req = RateLimitReq(name="lease", unique_key=key, hits=1,
                           limit=limit, duration=60_000)

        def admitted_of(resps):
            return sum(
                1 for r in resps
                if r.error == "" and r.status == Status.UNDER_LIMIT
            )

        lc = LeasedClient(
            d0.grpc_address, lease=lease_cfg, client_id="chaos-holder"
        )
        admitted = 0
        try:
            # Acquire the grant pre-partition.  The first check falls
            # back through the forward path (1 authoritative hit).
            admitted += admitted_of(lc.get_rate_limits([req]))
            deadline = time.monotonic() + 10.0
            while not any(
                v.allowance_left > 0 for v in lc.table._leases.values()
            ):
                if time.monotonic() > deadline:
                    raise AssertionError(
                        f"grant never arrived: {lc.stats()}"
                    )
                time.sleep(0.05)

            # PARTITION the owner away from the holder's proxy.
            injector.set_active(True)
            injector.partition(
                {owner.grpc_address},
                {d.grpc_address for d in cluster.daemons
                 if d is not owner},
            )

            # The partitioned holder burns its full grant — and NEVER
            # more: once the allowance is gone, fallbacks through the
            # dead forward path answer errors, not admissions.
            local_before = lc.stats()["local_admitted"]
            for _ in range(allowance + 30):
                admitted += admitted_of(lc.get_rate_limits([req]))
            local_burned = lc.stats()["local_admitted"] - local_before
            assert local_burned == allowance, (
                f"holder burned {local_burned}, grant was {allowance}"
            )

            # Direct traffic at the owner saturates the authoritative
            # row (its own clients are unaffected by the partition).
            cl_o = V1Client(owner.grpc_address)
            try:
                for _ in range(limit + 20):
                    admitted += admitted_of(
                        cl_o.get_rate_limits([req], timeout=30)
                    )
                bound = int(limit * (1 + holders * fraction))  # 250
                assert admitted == bound, (
                    f"admitted {admitted} != bound {bound}"
                )
                # Saturated: every further check everywhere denies.
                extra = admitted_of(
                    cl_o.get_rate_limits([req], timeout=30)
                ) + admitted_of(lc.get_rate_limits([req]))
                assert extra == 0, "admission past the proven bound"
            finally:
                cl_o.close()

            # HEAL.  Phase B on a FRESH key owned by the same daemon:
            # burned hits must reconcile into the owner's row exactly
            # once (queue_hit at-most-once through the proxy).
            injector.heal()
            key2 = next(
                f"M{i}" for i in range(1000)
                if cluster.owner_daemon_of(f"lease_M{i}") is owner
            )
            req2 = RateLimitReq(name="lease", unique_key=key2, hits=1,
                                limit=limit, duration=60_000)
            # Drive checks while waiting: each fallback re-requests the
            # grant once the refusal cooldown lapses (the d0->owner
            # breaker needs its half-open probe after the partition),
            # and every direct admission is counted for the
            # convergence arithmetic below.
            direct2 = 0
            deadline = time.monotonic() + 20.0
            while not any(
                v.allowance_left > 0
                for k, v in lc.table._leases.items()
                if k == f"lease_{key2}"
            ):
                direct2 += admitted_of(lc.get_rate_limits([req2]))
                if time.monotonic() > deadline:
                    raise AssertionError(
                        f"post-heal grant never arrived: {lc.stats()}"
                    )
                time.sleep(0.1)
            burn2 = 20
            for _ in range(burn2):
                r = lc.get_rate_limits([req2])[0]
                assert (r.metadata or {}).get("lease") == "local", r

            def converged():
                row = owner.service.backend.get_cache_item(
                    f"lease_{key2}"
                )
                return (
                    row is not None
                    and limit - int(row.remaining) == burn2 + direct2
                )

            deadline = time.monotonic() + 20.0
            while not converged():
                if time.monotonic() > deadline:
                    row = owner.service.backend.get_cache_item(
                        f"lease_{key2}"
                    )
                    raise AssertionError(
                        "burned hits never reconverged: row="
                        f"{row} expected {burn2 + direct2} applied"
                    )
                time.sleep(0.1)
        finally:
            lc.close()

        # Owner re-collects on heal: close() released the grants, so
        # the carve slots drop (RESET_REMAINING removes the rows) and
        # no holder state survives.
        deadline = time.monotonic() + 15.0
        while True:
            slots = [
                owner.service.backend.get_cache_item(
                    f"lease_{k}" + LEASE_SUFFIX
                )
                for k in (key, key2)
            ]
            vars_ = owner.service.leases.debug_vars()
            if all(s is None for s in slots) and not vars_["keys"]:
                break
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"owner never re-collected: slots={slots} "
                    f"holders={vars_['keys']}"
                )
            time.sleep(0.1)

        # The lease bound from the LIVE metrics surface
        # (docs/observability.md): each granted carve counts its
        # allowance as lease-grant over-admission at the owner — two
        # grants landed (key pre-partition, key2 post-heal) and the
        # per-window carve budget (allowance x max_holders) makes a
        # third carve impossible, so the merged ledger shows EXACTLY
        # 2 x allowance.  That is the live form of the paper's
        # limit x (1 + holders x fraction) admission bound.
        over = _merged_tenant(cluster.daemons, "lease")[
            "over_admitted"
        ].get("lease-grant", 0)
        assert over == 2 * allowance, (
            f"live lease-grant over-admission {over} != "
            f"2 x allowance {2 * allowance}"
        )

        print(
            f"lease smoke OK: seed={seed} key={hash_key} "
            f"owner={owner.grpc_address} admitted={admitted} "
            f"(bound {int(limit * (1 + holders * fraction))}), "
            f"holder burned {allowance}/{allowance} under partition, "
            f"reconverged +{burn2} after heal, slots re-collected"
        )
    except BaseException:
        _dump_flightrec(cluster, "lease-smoke-failure")
        raise
    finally:
        cluster.stop()


def reshard_scenario(seed: int) -> None:
    """Membership churn mid-traffic (docs/resharding.md acceptance)."""
    import time as _t

    from dataclasses import replace

    from gubernator_tpu.client import V1Client
    from gubernator_tpu.core.config import (
        DaemonConfig,
        ReshardConfig,
        fast_test_behaviors,
    )
    from gubernator_tpu.core.types import RateLimitReq, Status
    from gubernator_tpu.daemon import Daemon
    from gubernator_tpu.net.replicated_hash import (
        ReplicatedConsistentHash,
        xx_64,
    )
    from gubernator_tpu.core.types import PeerInfo
    from gubernator_tpu.testing import (
        ChaosInjector,
        ChaosPlan,
        Cluster,
        Rule,
    )
    from gubernator_tpu.testing.cluster import TEST_DEVICE

    limit, fraction = 200, 0.25
    injector = ChaosInjector(ChaosPlan(seed=seed))
    injector.set_active(False)  # boot runs clean
    conf = DaemonConfig(
        reshard=ReshardConfig(
            handoff_fraction=fraction, timeout_s=30.0,
            release_linger_s=2.0,
        ),
        chaos=injector,
        flightrec=True,
        flightrec_dir=os.environ.get(
            "GUBER_FLIGHTREC_DIR", "flightrec-dumps"
        ),
    )
    cluster = Cluster.start_with(["", "", ""], conf_template=conf)
    try:
        d0, d1, d2 = cluster.daemons

        # Boot the JOINER first (not yet in any ring) so its address —
        # and therefore which arcs move — is known up front.
        async def boot():
            c = replace(
                conf,
                grpc_listen_address="127.0.0.1:0",
                http_listen_address="127.0.0.1:0",
                behaviors=fast_test_behaviors(),
                device=TEST_DEVICE,
            )
            d = Daemon(c)
            await d.start()
            d.conf.advertise_address = d.grpc_address
            return d

        d3 = cluster.run(boot(), timeout=300.0)

        class _P:
            def __init__(self, addr):
                self._i = PeerInfo(grpc_address=addr)

            def info(self):
                return self._i

        def owner_addr(key, addrs):
            pick = ReplicatedConsistentHash(xx_64)
            for a in addrs:
                pick.add(_P(a))
            return pick.get(key).info().grpc_address

        three = [d.grpc_address for d in cluster.daemons]
        four = three + [d3.grpc_address]
        movers = [
            f"r{i}" for i in range(8000)
            if owner_addr(f"churn_r{i}", three) == d0.grpc_address
            and owner_addr(f"churn_r{i}", four) == d3.grpc_address
        ][:2]
        assert len(movers) == 2, "could not find moving keys"
        k_sat, k_cons = movers  # saturated key; conservation probe key
        req_sat = RateLimitReq(name="churn", unique_key=k_sat, hits=1,
                               limit=limit, duration=DURATION)
        req_cons = RateLimitReq(name="churn", unique_key=k_cons, hits=1,
                                limit=limit, duration=DURATION)

        cl = V1Client(d1.grpc_address)
        try:
            # Phase 0: saturate k_sat exactly; burn 30 on k_cons.
            admitted = 0
            for _ in range(limit + 20):
                r = cl.get_rate_limits([req_sat], timeout=30)[0]
                if r.error == "" and r.status == Status.UNDER_LIMIT:
                    admitted += 1
            assert admitted == limit, f"saturation {admitted} != {limit}"
            burned = 30
            for _ in range(burned):
                r = cl.get_rate_limits([req_cons], timeout=30)[0]
                assert r.error == "" and r.status == Status.UNDER_LIMIT
            pre = d0.service.backend.get_cache_item(f"churn_{k_cons}")
            assert int(pre.remaining) == limit - burned

            # Phase 1: JOIN with every Migrate chunk chaos-failed —
            # the handoff window stays open under live traffic.
            injector.reset(ChaosPlan(seed=seed, rules=[
                Rule(op="error", where="client", method="Migrate",
                     probability=1.0, status="UNAVAILABLE",
                     message="injected: migrate blackhole"),
            ]))
            injector.set_active(True)
            cluster.daemons.append(d3)
            cluster.run(cluster._push_peers(), timeout=60.0)
            deadline = _t.monotonic() + 30.0
            while _t.monotonic() < deadline:
                ib = d3.service.reshard._inbound.get(d0.grpc_address)
                if ib is not None and ib.phase == "transfer":
                    break
                _t.sleep(0.1)
            else:
                raise AssertionError("handoff never reached transfer")

            # The saturated key admits EXACTLY fraction x limit more
            # through the joiner's bounded shadow — never one hit over.
            budget = int(limit * fraction)
            shadow_admitted = 0
            for _ in range(budget + 30):
                r = cl.get_rate_limits([req_sat], timeout=30)[0]
                assert r.error == "", r
                if r.status == Status.UNDER_LIMIT:
                    shadow_admitted += 1
            assert shadow_admitted == budget, (
                f"shadow admitted {shadow_admitted} != {budget}"
            )
            total = admitted + shadow_admitted
            bound = int(limit * (1 + fraction))
            assert total == bound, f"admitted {total} != bound {bound}"
            # The same bound from the LIVE metrics surface
            # (docs/observability.md): every admission past the base
            # limit rode the joiner's handoff shadow, so the merged
            # ledger's handoff-shadow over-admission is EXACTLY the
            # handoff_fraction x limit budget — limit x (1 + fraction)
            # as an operator-visible number.
            over = _merged_tenant(cluster.daemons, "churn")[
                "over_admitted"
            ].get("handoff-shadow", 0)
            assert over == budget, (
                f"live handoff-shadow over-admission {over} != "
                f"budget {budget}"
            )

            # Phase 2: HEAL — the transfer completes, the shadow burns
            # reconcile, and the new owner is authoritative.
            injector.heal()
            deadline = _t.monotonic() + 30.0
            while _t.monotonic() < deadline:
                rs0 = d0.service.reshard
                if rs0.handoffs_started and rs0.handoffs_started == (
                    rs0.handoffs_completed + rs0.handoffs_aborted
                ) and not d3.service.reshard._inbound:
                    break
                _t.sleep(0.1)
            assert d0.service.reshard.handoffs_completed >= 1, (
                d0.service.reshard.debug_vars()
            )
            # No orphaned slots at the demoted owner.
            assert d0.service.backend.get_cache_item(
                f"churn_{k_sat}"
            ) is None
            assert d0.service.backend.get_cache_item(
                f"churn_{k_cons}"
            ) is None
            # Saturated + reconciled: every further check denies.
            r = cl.get_rate_limits([req_sat], timeout=30)[0]
            assert r.status == Status.OVER_LIMIT, r
            # pymodel continuation on the conserved key: remaining
            # continues the ORIGINAL window at the new owner.
            row = d3.service.backend.get_cache_item(f"churn_{k_cons}")
            assert row is not None
            assert int(row.remaining) == limit - burned
            assert row.created_at == pre.created_at
            r = cl.get_rate_limits([req_cons], timeout=30)[0]
            assert r.status == Status.UNDER_LIMIT
            assert int(r.remaining) == limit - burned - 1
            assert r.reset_time == pre.created_at + DURATION

            # Phase 3: graceful LEAVE — the joiner drains back out;
            # counters survive the second remap too.
            shipped = cluster.run(d3.drain(), timeout=60.0)
            assert shipped >= 2, f"drain shipped {shipped} rows"
            cluster.daemons.remove(d3)
            cluster.run(cluster._push_peers(), timeout=60.0)
            survivor_addr = owner_addr(f"churn_{k_cons}", three)
            survivor = next(
                d for d in cluster.daemons
                if d.grpc_address == survivor_addr
            )
            row = survivor.service.backend.get_cache_item(
                f"churn_{k_cons}"
            )
            assert row is not None
            assert int(row.remaining) == limit - burned - 1
            r = cl.get_rate_limits([req_cons], timeout=30)[0]
            assert r.status == Status.UNDER_LIMIT
            assert int(r.remaining) == limit - burned - 2
            cluster.run(d3.close(), timeout=60.0)

            print(
                f"reshard smoke OK: seed={seed} key={k_sat} "
                f"admitted={total} == bound {bound} exactly, "
                f"conserved key continued at "
                f"{limit - burned - 2}/{limit} across join+leave, "
                f"rows sent={d0.service.reshard.rows_sent}"
                f"+drain {shipped}, no orphaned slots"
            )
        finally:
            cl.close()
    except BaseException:
        _dump_flightrec(cluster, "reshard-smoke-failure")
        raise
    finally:
        cluster.stop()


def coldstorm_scenario(seed: int) -> None:
    """The Guberberg tier storm (docs/tiering.md): a keyspace 8x the
    HBM slot budget through a live tier-enabled daemon.  Asserts the
    documented over-admission bound from the merged /debug/vars ledger
    (allowed <= limit x (keys + demote/promote cycles)), that the tier
    actually cycled (demotes, cold hits, promotes all nonzero), then
    kill + restart: the checkpoint must restore BOTH tiers — cold
    residents conserved, HBM occupancy restored, and an exhausted key
    still denied (no limit reset across the restart)."""
    import shutil
    import tempfile

    from gubernator_tpu.cli import gubtop
    from gubernator_tpu.client import V1Client
    from gubernator_tpu.core.config import (
        DaemonConfig,
        DeviceConfig,
        TierConfig,
    )
    from gubernator_tpu.core.types import RateLimitReq, Status
    from gubernator_tpu.runtime.checkpoint import TableCheckpointer
    from gubernator_tpu.testing import Cluster
    from gubernator_tpu.testing.chaos import zipf_keys

    SLOTS = 1024
    NKEYS = SLOTS * 8
    CLIMIT = 50
    CDUR = 300_000  # outlives the smoke — nothing expires mid-run
    dev = DeviceConfig(num_slots=SLOTS, ways=8, batch_size=512)

    def tiered_conf() -> DaemonConfig:
        return DaemonConfig(
            tier=TierConfig(
                enabled=True, cold_capacity=NKEYS * 2,
                high_water=0.60, low_water=0.40,
                demote_batch=256, interval_s=0.15,
            ),
            flightrec=True,
            flightrec_dir=os.environ.get(
                "GUBER_FLIGHTREC_DIR", "flightrec-dumps"
            ),
        )

    def drive(cl, keys, hits=1):
        """One admission sweep; returns per-key OK counts."""
        ok = {}
        for lo in range(0, len(keys), 500):
            chunk = keys[lo:lo + 500]
            resp = cl.get_rate_limits([
                RateLimitReq(
                    name="coldstorm", unique_key=k, hits=hits,
                    limit=CLIMIT, duration=CDUR,
                )
                for k in chunk
            ], timeout=60)
            for k, r in zip(chunk, resp):
                if r.error == "" and r.status == Status.UNDER_LIMIT:
                    ok[k] = ok.get(k, 0) + hits
        return ok

    def settle(d, deadline_s=15.0):
        """Drain queued promotes so the counters/cold census are
        stable before we assert on them (drain_promotes_sync is the
        TierManager's test/smoke entry point)."""
        t1 = time.monotonic() + deadline_s
        while time.monotonic() < t1:
            tm = d.tier
            if tm is None or tm.drain_promotes_sync() == 0:
                return
            time.sleep(0.05)

    ckdir = tempfile.mkdtemp(prefix="coldstorm-ck-")
    cluster = Cluster.start_with(
        [""], device=dev, conf_template=tiered_conf()
    )
    try:
        d0 = cluster.daemons[0]
        keys = [f"c{i}" for i in range(NKEYS)]
        ok = {k: 0 for k in keys}
        cl = V1Client(cluster.addresses()[0])
        try:
            # Pass 1: the full keyspace once — 8x the slot budget
            # cannot be HBM-resident, so the watermark loop must cycle
            # rows through the cold tier for the daemon to keep
            # serving.
            for k, n in drive(cl, keys).items():
                ok[k] += n
            # Pass 2: seeded zipfian reuse — hot ranks re-hit keys the
            # watermark already demoted, driving cold hits + promotes
            # (and pushing hot keys past one limit window, so the
            # over-admission bound below is load-bearing, not slack).
            for _round in range(6):
                draws = zipf_keys(seed + _round, 1.3, 2000, NKEYS)
                reuse = [f"c{i}" for i in sorted(set(draws))]
                for k, n in drive(cl, reuse).items():
                    ok[k] += n
                time.sleep(0.2)  # let watermark ticks interleave
            settle(d0)

            # The merged production ledger (/debug/vars via gubtop),
            # never test internals.
            scrape = gubtop.scrape(d0.http_address)
            assert "error" not in scrape, scrape
            tier = scrape.get("tier") or {}
            assert tier, "/debug/vars has no tier block"
            assert tier["demotes"] > 0, (
                f"no demotions under 8x slot pressure: {tier}"
            )
            assert tier["cold_hits"] > 0 and tier["promotes"] > 0, (
                f"zipfian reuse never hit the cold tier: {tier}"
            )
            tenant = _merged_tenant(cluster.daemons, "coldstorm")
            allowed = tenant["allowed"]
            client_ok = sum(ok.values())
            served = sum(1 for k in keys if ok[k] > 0)
            assert served >= NKEYS * 0.95, (
                f"only {served}/{NKEYS} keys admitted at least once"
            )
            # docs/tiering.md bound: each demote/promote cycle widens a
            # key's admission by at most ONE limit window, so
            # cluster-wide: allowed <= limit x (keys + cycles), and
            # every cycle begins with a demotion.
            bound = CLIMIT * (NKEYS + tier["demotes"])
            assert allowed <= bound, (
                f"tier over-admission past the documented bound: "
                f"allowed={allowed} > {bound} "
                f"(= {CLIMIT} x ({NKEYS} keys + {tier['demotes']} "
                f"demotes))"
            )
            assert allowed >= client_ok, (
                f"ledger allowed={allowed} < client-observed "
                f"{client_ok}"
            )

            # Freeze the watermark loop so the exhaust -> save window
            # is race-free (close() is idempotent; the daemon's own
            # shutdown calls it again), then exhaust one key
            # completely and checkpoint BOTH tiers.
            settle(d0)
            d0.tier.close()
            probe = "c0"
            denied = False
            for _ in range(2 * CLIMIT + 2):
                r = cl.get_rate_limits([RateLimitReq(
                    name="coldstorm", unique_key=probe, hits=1,
                    limit=CLIMIT, duration=CDUR,
                )], timeout=60)[0]
                assert r.error == "", r
                if r.status == Status.OVER_LIMIT:
                    denied = True
                    break
            assert denied, "probe key never exhausted pre-restart"
            cold_before = d0.tier.cold.residents()
            occ_before = d0.service.backend.occupancy()
            assert cold_before > 0, "nothing cold-resident at save"
            ck = TableCheckpointer(ckdir)
            ck.save(d0.service.backend, step=1, coldtier=d0.tier.cold)
        finally:
            cl.close()
    except BaseException:
        _dump_flightrec(cluster, "coldstorm-failure")
        cluster.stop()
        shutil.rmtree(ckdir, ignore_errors=True)
        raise
    else:
        cluster.stop()  # the kill

    # Restart: a fresh daemon restores both tiers from the checkpoint.
    cluster = Cluster.start_with(
        [""], device=dev, conf_template=tiered_conf()
    )
    try:
        d1 = cluster.daemons[0]
        ck = TableCheckpointer(ckdir)
        ck.restore(d1.service.backend, coldtier=d1.tier.cold)
        cold_after = d1.tier.cold.residents()
        occ_after = d1.service.backend.occupancy()
        assert cold_after == cold_before, (
            f"cold tier not conserved across restart: "
            f"{cold_before} -> {cold_after}"
        )
        assert occ_after == occ_before, (
            f"HBM tier not conserved across restart: "
            f"{occ_before} -> {occ_after}"
        )
        cl = V1Client(cluster.addresses()[0])
        try:
            r = cl.get_rate_limits([RateLimitReq(
                name="coldstorm", unique_key="c0", hits=1,
                limit=CLIMIT, duration=CDUR,
            )], timeout=60)[0]
            assert r.status == Status.OVER_LIMIT, (
                f"restart reset the limit: exhausted key admitted "
                f"again ({r})"
            )
        finally:
            cl.close()
        print(
            f"coldstorm smoke OK: seed={seed} keyspace={NKEYS} "
            f"(8x {SLOTS} slots), served={served}, "
            f"demotes={tier['demotes']} promotes={tier['promotes']} "
            f"cold_hits={tier['cold_hits']}, allowed={allowed} <= "
            f"bound={bound}, restart conserved "
            f"(cold={cold_after}, hbm={occ_after}), no limit reset"
        )
    except BaseException:
        _dump_flightrec(cluster, "coldstorm-restart-failure")
        raise
    finally:
        cluster.stop()
        shutil.rmtree(ckdir, ignore_errors=True)


def regionsplit_scenario(seed: int) -> None:
    """Planet-scale region partition (docs/multiregion.md acceptance)."""
    from gubernator_tpu.client import V1Client
    from gubernator_tpu.core.config import (
        CircuitConfig,
        DaemonConfig,
        RegionConfig,
    )
    from gubernator_tpu.core.types import RateLimitReq, Status
    from gubernator_tpu.testing import ChaosInjector, ChaosPlan, Cluster

    limit = 200
    fraction = 0.25
    carve = int(limit * fraction)  # 50
    bound = int(limit * (1 + 1 * fraction))  # 250: one remote region
    injector = ChaosInjector(ChaosPlan(seed=seed))
    injector.set_active(False)  # boot runs clean
    cluster = Cluster.start_with(
        ["east", "east", "west", "west"],
        conf_template=DaemonConfig(
            region=RegionConfig(
                enabled=True, fraction=fraction, reconcile_ms=200,
                drift_max=10_000,
            ),
            circuit=CircuitConfig(
                failure_threshold=3, base_backoff_s=0.1,
                max_backoff_s=1.0, jitter=0.2,
            ),
            chaos=injector,
            flightrec=True,
            flightrec_dir=os.environ.get(
                "GUBER_FLIGHTREC_DIR", "flightrec-dumps"
            ),
        ),
    )
    try:
        east = [d for d in cluster.daemons if d.conf.data_center == "east"]
        west = [d for d in cluster.daemons if d.conf.data_center == "west"]
        rm = east[0].service.regions
        assert sorted(rm.universe()) == ["east", "west"], rm.universe()
        # Every daemon agrees on every home pick (the rendezvous needs
        # only the shared universe, no coordination rounds).
        for i in range(20):
            homes = {
                d.service.regions.home_region(f"region_R{i}")
                for d in cluster.daemons
            }
            assert len(homes) == 1, f"home split-brain for R{i}: {homes}"

        def admitted_of(resps):
            return sum(
                1 for r in resps
                if r.error == "" and r.status == Status.UNDER_LIMIT
            )

        def east_region_vars():
            return [d.service.regions.debug_vars() for d in east]

        # -- phase A (healthy WAN): the carve serves a west-homed key
        # from east with zero WAN RTT, and the burns reconcile into
        # the home region's row exactly once.
        warm = next(
            f"H{i}" for i in range(1000)
            if rm.home_region(f"regionwarm_H{i}") == "west"
        )
        warm_req = RateLimitReq(name="regionwarm", unique_key=warm,
                                hits=1, limit=limit, duration=DURATION)
        warm_burn = 5
        cl_e = [V1Client(d.grpc_address) for d in east]
        cl_w = V1Client(west[0].grpc_address)
        try:
            for i in range(warm_burn):
                r = cl_e[i % 2].get_rate_limits([warm_req], timeout=30)[0]
                assert r.error == "" and r.status == Status.UNDER_LIMIT, r
                md = r.metadata or {}
                assert md.get("region") == "west", md
                assert md.get("region_serve") == "carve", md
            deadline = time.monotonic() + 15.0
            while sum(v["drift"] for v in east_region_vars()) > 0:
                if time.monotonic() > deadline:
                    raise AssertionError(
                        "healthy-WAN drift never drained: "
                        f"{east_region_vars()}"
                    )
                time.sleep(0.1)
            consumed = sum(
                limit - int(row.remaining)
                for d in west
                for row in [d.service.backend.get_cache_item(
                    f"regionwarm_{warm}"
                )]
                if row is not None
            )
            assert consumed == warm_burn, (
                f"home region absorbed {consumed} != {warm_burn} "
                "burned carve hits (reconcile must be at-most-once)"
            )

            # -- phase B: PARTITION the regions mid-traffic.  The main
            # key is untouched until now, so the bound arithmetic is
            # exact: carve admissions all happen under partition.
            key = next(
                f"R{i}" for i in range(1000)
                if rm.home_region(f"region_R{i}") == "west"
            )
            req = RateLimitReq(name="region", unique_key=key, hits=1,
                               limit=limit, duration=DURATION)
            injector.set_active(True)
            injector.partition(
                {d.grpc_address for d in east},
                {d.grpc_address for d in west},
            )

            # The dark side serves EXACTLY its carve and never more:
            # east keeps answering from the bounded `.region-carve`
            # slot while the WAN is severed.
            admitted = 0
            for i in range(carve + 30):
                admitted += admitted_of(
                    cl_e[i % 2].get_rate_limits([req], timeout=30)
                )
            assert admitted == carve, (
                f"east admitted {admitted} != carve {carve}"
            )
            # The un-reconciled backlog IS the divergence, observable.
            vars_e = east_region_vars()
            assert sum(v["drift"] for v in vars_e) == carve, vars_e
            deadline = time.monotonic() + 15.0
            while not any(
                lk["state"] == "degraded"
                for v in east_region_vars()
                for lk in v["links"].values()
            ):
                if time.monotonic() > deadline:
                    raise AssertionError(
                        "link never marked degraded under partition: "
                        f"{east_region_vars()}"
                    )
                time.sleep(0.1)

            # The home region is unaffected: direct traffic saturates
            # the authoritative row at the full limit.
            for _ in range(limit + 20):
                admitted += admitted_of(
                    cl_w.get_rate_limits([req], timeout=30)
                )
            assert admitted == bound, (
                f"admitted {admitted} != bound {bound} "
                f"(limit x (1 + regions x fraction))"
            )
            # Saturated on BOTH sides of the split: not one hit over.
            extra = sum(
                admitted_of(c.get_rate_limits([req], timeout=30))
                for c in (cl_e[0], cl_e[1], cl_w)
            )
            assert extra == 0, "admission past the proven bound"

            # The bound from the LIVE metrics surface
            # (docs/observability.md): every carve admission counts as
            # region-carve over-admission in the merged tenant ledger —
            # EXACTLY the carve, nothing more, even mid-partition.
            over = _merged_tenant(cluster.daemons, "region")[
                "over_admitted"
            ].get("region-carve", 0)
            assert over == carve, (
                f"live region-carve over-admission {over} != {carve}"
            )

            # -- phase C: HEAL.  The backlog flushes at-most-once, the
            # link re-homes (REGION_PREPARE -> TRANSFER -> CUTOVER),
            # drift reconverges to zero, and nothing double counts.
            injector.heal()
            deadline = time.monotonic() + 20.0
            while True:
                vars_e = east_region_vars()
                drained = sum(v["drift"] for v in vars_e) == 0
                rehomed = all(
                    lk["state"] == "remote"
                    for v in vars_e for lk in v["links"].values()
                )
                if drained and rehomed:
                    break
                if time.monotonic() > deadline:
                    raise AssertionError(
                        f"drift never reconverged after heal: {vars_e}"
                    )
                time.sleep(0.1)
            vars_e = east_region_vars()
            assert sum(v["rehomes"] for v in vars_e) >= 1, vars_e
            assert sum(v["reconcile_dropped"] for v in vars_e) == 0, (
                f"at-most-once violated (ambiguous drops): {vars_e}"
            )
            # The late burns landed on a SATURATED home row (denied,
            # never re-admitted) and the carve slot kept its consumed
            # state through cutover — no per-heal budget refresh, so
            # the key stays exhausted everywhere.
            extra = sum(
                admitted_of(c.get_rate_limits([req], timeout=30))
                for c in (cl_e[0], cl_e[1], cl_w)
            )
            assert extra == 0, "heal re-admitted past the bound"
            over = _merged_tenant(cluster.daemons, "region")[
                "over_admitted"
            ].get("region-carve", 0)
            assert over == carve, (
                f"post-heal region-carve over-admission {over} != "
                f"{carve} (reconcile double counted)"
            )
        finally:
            for c in cl_e:
                c.close()
            cl_w.close()

        print(
            f"regionsplit smoke OK: seed={seed} key=region_{key} "
            f"home=west carve={carve}, admitted={bound} == "
            f"limit x (1 + 1 x {fraction}), drift {carve}->0 after "
            f"heal, rehomed, ledger region-carve == {carve} exactly"
        )
    except BaseException:
        _dump_flightrec(cluster, "regionsplit-smoke-failure")
        raise
    finally:
        cluster.stop()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=1337)
    ap.add_argument(
        "--scenario",
        choices=(
            "storm", "hotkey", "lease", "reshard", "coldstorm",
            "regionsplit", "all"
        ),
        default="storm",
    )
    args = ap.parse_args()
    if args.scenario in ("storm", "all"):
        storm_scenario(args.seed)
    if args.scenario in ("hotkey", "all"):
        hotkey_scenario(args.seed)
    if args.scenario in ("lease", "all"):
        lease_scenario(args.seed)
    if args.scenario in ("reshard", "all"):
        reshard_scenario(args.seed)
    if args.scenario in ("coldstorm", "all"):
        coldstorm_scenario(args.seed)
    if args.scenario in ("regionsplit", "all"):
        regionsplit_scenario(args.seed)


if __name__ == "__main__":
    main()
