"""CI smoke: a short seeded fault plan against a 3-daemon in-process
cluster (the compressed version of tests/test_chaos.py).

Boots three real daemons on one loop with per-peer circuit breakers,
`local_shadow` degraded mode and the flight recorder armed, injects a
seeded storm of client/server faults (>=30% of peer RPCs fail), then
asserts the resilience invariants end to end:

  * zero double counts — every key's applied hits on its owner equal
    exactly the successful responses the client saw;
  * at least one breaker tripped during the storm;
  * after heal, every opened breaker re-closes and forwards succeed.

On any failure each daemon's flight recorder dumps its ring to
GUBER_FLIGHTREC_DIR (default flightrec-dumps/) so the CI artifact step
can pick the evidence up.

Run from the repo root:  python scripts/chaos_smoke.py [--seed N]
The whole run is deterministic given the seed (docs/resilience.md).
"""
from __future__ import annotations

import argparse
import os
import random
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Runnable from a checkout without an installed package.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LIMIT = 1000
DURATION = 60_000
KEYS = 20
ROUNDS = 5


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=1337)
    args = ap.parse_args()

    from gubernator_tpu.client import V1Client
    from gubernator_tpu.core.config import CircuitConfig, DaemonConfig
    from gubernator_tpu.core.types import RateLimitReq
    from gubernator_tpu.testing import (
        ChaosInjector,
        ChaosPlan,
        Cluster,
        Rule,
    )

    injector = ChaosInjector(ChaosPlan(seed=args.seed))
    injector.set_active(False)  # boot/peer-discovery runs clean
    cluster = Cluster.start_with(
        ["", "", ""],
        conf_template=DaemonConfig(
            # Fast breaker schedule so open -> half-open -> re-close
            # cycles fit a smoke budget.
            circuit=CircuitConfig(
                failure_threshold=3, base_backoff_s=0.1,
                max_backoff_s=1.0, jitter=0.2,
            ),
            degraded_mode="local_shadow",
            shadow_fraction=0.25,
            chaos=injector,
            flightrec=True,
            flightrec_dir=os.environ.get(
                "GUBER_FLIGHTREC_DIR", "flightrec-dumps"
            ),
        ),
    )

    def dump_flightrec(reason: str) -> None:
        for d in cluster.daemons:
            if d.flightrec is not None:
                path = cluster.run(d.flightrec.dump(reason))
                print(f"flightrec dump ({d.grpc_address}): {path}")

    try:
        # The same fault mix as test_seeded_plan_no_double_count, with
        # the hard-failure rates bumped so the >=30% floor holds at
        # smoke sample sizes: unsent client errors (retry-safe),
        # pre-apply server rejections, drops and delays.
        injector.reset(ChaosPlan(seed=args.seed, rules=[
            Rule(op="error", where="client", method="GetPeerRateLimits",
                 probability=0.28, status="UNAVAILABLE",
                 message="injected: failed to connect to all addresses"),
            Rule(op="error", where="server", phase="before",
                 method="GetPeerRateLimits", probability=0.15,
                 status="UNAVAILABLE",
                 message="injected: refused before apply"),
            Rule(op="drop", where="client", method="GetPeerRateLimits",
                 probability=0.04, delay_s=0.01),
            Rule(op="delay", where="client", method="GetPeerRateLimits",
                 probability=0.10, delay_s=0.005),
        ]))

        keys = [f"smoke{i}" for i in range(KEYS)]
        ok = {k: 0 for k in keys}
        cl = V1Client(cluster.addresses()[0])
        try:
            for _round in range(ROUNDS):
                for k in keys:
                    r = cl.get_rate_limits([
                        RateLimitReq(
                            name="chaos", unique_key=k, hits=1,
                            limit=LIMIT, duration=DURATION,
                        )
                    ], timeout=30)[0]
                    if r.error == "" and "degraded" not in (r.metadata or {}):
                        ok[k] += 1
        finally:
            cl.close()

        frac = injector.failure_fraction()
        assert frac >= 0.30, (
            f"storm too mild: {frac:.0%} injected failures "
            f"({dict(injector.injected)})"
        )

        forwarded = 0
        for k in keys:
            hash_key = f"chaos_{k}"
            owner = cluster.owner_daemon_of(hash_key)
            if owner is not cluster.daemons[0]:
                forwarded += 1
            it = owner.service.backend.get_cache_item(hash_key)
            applied = 0 if it is None else LIMIT - int(it.remaining)
            assert applied == ok[k], (
                f"key {k}: owner applied {applied}, client saw "
                f"{ok[k]} successes — double count or lost hit"
            )
        assert forwarded >= 5, f"only {forwarded} keys forwarded"

        trips = sum(
            p.breaker.trips
            for d in cluster.daemons
            for p in d.service.peer_list()
            if p.breaker is not None and not p.info().is_owner
        )
        assert trips >= 1, "no breaker tripped during the storm"

        # Heal; probe from every daemon until every breaker re-closes.
        injector.heal()
        clients = [V1Client(a) for a in cluster.addresses()]
        try:
            deadline = time.monotonic() + 20.0
            while True:
                for c2 in clients:
                    c2.get_rate_limits([
                        RateLimitReq(
                            name="quiesce",
                            unique_key=f"q{random.random()}",
                            hits=1, limit=LIMIT, duration=DURATION,
                        )
                        for _ in range(4)
                    ], timeout=30)
                states = cluster.breaker_states()
                stuck = [
                    (a, pa, s)
                    for a, peers in states.items()
                    for pa, s in peers.items()
                    if s not in ("closed", "disabled")
                ]
                if not stuck:
                    break
                if time.monotonic() > deadline:
                    raise AssertionError(
                        f"breakers never re-closed after heal: {stuck}"
                    )
                time.sleep(0.1)
        finally:
            for c2 in clients:
                c2.close()

        print(
            f"chaos smoke OK: seed={args.seed} "
            f"injected={frac:.0%} of {injector.attempts['client']} "
            f"client RPCs, trips={trips}, forwarded_keys={forwarded}, "
            f"all breakers re-closed"
        )
    except BaseException:
        dump_flightrec("chaos-smoke-failure")
        raise
    finally:
        cluster.stop()


if __name__ == "__main__":
    main()
