// gubernator-tpu native host runtime.
//
// The device step is sub-millisecond; at batch_limit-scale traffic the
// host-side request packing (per-key string hashing + duplicate-round
// assignment) dominates when done in Python.  This library provides the two
// hot host ops over raw buffers, exposed via a C ABI for ctypes
// (gubernator_tpu/native/__init__.py):
//
//   gub_xxh64_batch    — XXH64 of N length-prefixed keys (the device
//                        fingerprint; matches python-xxhash seed 0)
//   gub_assign_rounds  — the packer's (round, lane) assignment with
//                        per-(round, shard) lane counters and hash-level
//                        duplicate detection (ops/batch.py's contract:
//                        occurrence k of a key lands in a strictly later
//                        round than occurrence k-1)
//
// Build: make -C native  (g++ -O3 -shared; no external dependencies —
// XXH64 is implemented from its public spec below).

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// XXH64 (from the xxHash spec; seed fixed to 0 like core/hashing.py)
// ---------------------------------------------------------------------------

static const uint64_t P1 = 11400714785074694791ULL;
static const uint64_t P2 = 14029467366897019727ULL;
static const uint64_t P3 = 1609587929392839161ULL;
static const uint64_t P4 = 9650029242287828579ULL;
static const uint64_t P5 = 2870177450012600261ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86/arm64)
}

static inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

static inline uint64_t xxh64_round(uint64_t acc, uint64_t input) {
  acc += input * P2;
  acc = rotl64(acc, 31);
  acc *= P1;
  return acc;
}

static inline uint64_t xxh64_merge(uint64_t acc, uint64_t val) {
  val = xxh64_round(0, val);
  acc ^= val;
  acc = acc * P1 + P4;
  return acc;
}

static uint64_t xxh64(const uint8_t* p, size_t len) {
  const uint8_t* end = p + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = P1 + P2, v2 = P2, v3 = 0, v4 = 0 - P1;
    const uint8_t* limit = end - 32;
    do {
      v1 = xxh64_round(v1, read64(p));
      v2 = xxh64_round(v2, read64(p + 8));
      v3 = xxh64_round(v3, read64(p + 16));
      v4 = xxh64_round(v4, read64(p + 24));
      p += 32;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = xxh64_merge(h, v1);
    h = xxh64_merge(h, v2);
    h = xxh64_merge(h, v3);
    h = xxh64_merge(h, v4);
  } else {
    h = P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    h ^= xxh64_round(0, read64(p));
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= (uint64_t)read32(p) * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * P5;
    h = rotl64(h, 11) * P1;
    p++;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

// Hash n keys packed as a concatenated blob with (n+1) byte offsets.
// out[i] = xxh64(blob[offsets[i]:offsets[i+1]]), remapped 0 -> 1 (the
// empty-slot sentinel rule, core/hashing.py key_hash64).
void gub_xxh64_batch(const uint8_t* blob, const int64_t* offsets, int64_t n,
                     int64_t* out) {
  for (int64_t i = 0; i < n; i++) {
    uint64_t h =
        xxh64(blob + offsets[i], (size_t)(offsets[i + 1] - offsets[i]));
    if (h == 0) h = 1;
    out[i] = (int64_t)h;
  }
}

// ---------------------------------------------------------------------------
// Round/lane assignment (ops/batch.py pack_requests_grid inner loop)
// ---------------------------------------------------------------------------

// Open-addressing map from key hash -> last assigned round (linear probe).
struct RoundMap {
  std::vector<uint64_t> keys;
  std::vector<int32_t> last_round;
  uint64_t mask;
  explicit RoundMap(int64_t n) {
    uint64_t cap = 16;
    while (cap < (uint64_t)n * 2) cap <<= 1;
    keys.assign(cap, 0);
    last_round.assign(cap, -1);
    mask = cap - 1;
  }
  int32_t* slot(uint64_t h) {
    uint64_t i = (h * P1) & mask;
    while (keys[i] != 0 && keys[i] != h) i = (i + 1) & mask;
    keys[i] = h;
    return &last_round[i];
  }
};

// Assign each request a (round, lane) such that:
//  - a key hash appears at most once per round,
//  - occurrence k of a key lands in a strictly later round than k-1,
//  - each (round, shard) holds at most batch_size lanes.
// hashes[i] == 0 marks an errored request (skipped; round=-1).
// Returns the number of rounds.
int64_t gub_assign_rounds(const int64_t* hashes, const int32_t* shards,
                          int64_t n, int32_t n_shards, int32_t batch_size,
                          int32_t* out_round, int32_t* out_lane) {
  RoundMap seen(n);
  // counters[r * n_shards + s] = lanes used; keysets per round for the
  // "key not in round" check are implied by last_round tracking: a key's
  // next occurrence starts probing at last_round+1, and WITHIN one probe
  // sequence only capacity can force extra rounds, never the same key.
  std::vector<int32_t> counters;
  int64_t n_rounds = 0;
  for (int64_t i = 0; i < n; i++) {
    uint64_t h = (uint64_t)hashes[i];
    if (h == 0) {
      out_round[i] = -1;
      out_lane[i] = -1;
      continue;
    }
    int32_t s = shards ? shards[i] : 0;
    int32_t* lr = seen.slot(h);
    int32_t r = *lr + 1;
    for (;;) {
      if (r >= n_rounds) {
        counters.resize((size_t)(r + 1) * n_shards, 0);
        n_rounds = r + 1;
      }
      int32_t& c = counters[(size_t)r * n_shards + s];
      if (c < batch_size) {
        out_round[i] = r;
        out_lane[i] = c;
        c++;
        *lr = r;
        break;
      }
      r++;
    }
  }
  return n_rounds;
}

// ---------------------------------------------------------------------------
// Protobuf wire codec for the GetRateLimits hot path.
//
// The python-protobuf parse/build of a 1000-item batch costs ~1ms each way —
// more than the device step itself.  These two functions move the whole
// request->columns and columns->response conversion to compiled code, the
// analog of the reference's generated Go marshalers: the daemon's fast lane
// hands the raw gRPC payload here and gets numpy columns back, and the
// response bytes are emitted directly from the packed device output arrays.
//
// Wire schema (proto/gubernator.proto): GetRateLimitsReq{repeated
// RateLimitReq requests = 1} with RateLimitReq fields name=1 unique_key=2
// hits=3 limit=4 duration=5 algorithm=6 behavior=7 burst=8;
// GetRateLimitsResp{repeated RateLimitResp responses = 1} with
// status=1 limit=2 remaining=3 reset_time=4 error=5.  (peers.proto's
// GetPeerRateLimits pair uses field 1 for the same item types, so the same
// codec serves the peer-to-peer hot path.)
// ---------------------------------------------------------------------------

static inline bool get_varint(const uint8_t*& p, const uint8_t* end,
                              uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  while (p < end && shift < 64) {
    uint8_t b = *p++;
    v |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;
}

static inline bool skip_field(const uint8_t*& p, const uint8_t* end,
                              uint32_t wire) {
  uint64_t tmp;
  switch (wire) {
    case 0:
      return get_varint(p, end, &tmp);
    case 1:
      if (end - p < 8) return false;
      p += 8;
      return true;
    case 2:
      if (!get_varint(p, end, &tmp) || (uint64_t)(end - p) < tmp)
        return false;
      p += tmp;
      return true;
    case 5:
      if (end - p < 4) return false;
      p += 4;
      return true;
    default:
      return false;
  }
}

// Count the repeated field-1 submessages of a GetRateLimitsReq (or
// GetPeerRateLimitsReq) payload.  Returns -1 on malformed input.
int64_t gub_count_reqs(const uint8_t* buf, int64_t len) {
  const uint8_t* p = buf;
  const uint8_t* end = buf + len;
  int64_t n = 0;
  while (p < end) {
    uint64_t tag;
    if (!get_varint(p, end, &tag)) return -1;
    if ((tag >> 3) == 1 && (tag & 7) == 2) {
      uint64_t sz;
      if (!get_varint(p, end, &sz) || (uint64_t)(end - p) < sz) return -1;
      p += sz;
      n++;
    } else {
      if (!skip_field(p, end, (uint32_t)(tag & 7))) return -1;
    }
  }
  return n;
}

// FNV-1 / FNV-1a (core/hashing.py fnv1_64 / fnv1a_64; the reference ring's
// key hash, replicated_hash.go:33) of each request's hash key
// (name + "_" + unique_key), re-walked from the spliced request frames
// (msg_off/msg_len from gub_parse_reqs2).  variant: 0 = fnv1
// (multiply-then-xor), 1 = fnv1a (xor-then-multiply).  out[i] = 0 when the
// frame has no name or key (errored lanes; the router masks them anyway).
// Keeps the columnar router serving under placement-interop rings in mixed
// reference/tpu clusters instead of falling back to per-request routing.
void gub_fnv_hashkey_batch(const uint8_t* buf, const int64_t* msg_off,
                           const int64_t* msg_len, int64_t n,
                           int32_t variant, int64_t* out) {
  const uint64_t PRIME = 1099511628211ULL;
  const uint64_t OFFSET = 14695981039346656037ULL;
  for (int64_t i = 0; i < n; i++) {
    const uint8_t* p = buf + msg_off[i];
    const uint8_t* fend = p + msg_len[i];
    out[i] = 0;
    uint64_t tag, sz;
    if (!get_varint(p, fend, &tag)) continue;
    if (!get_varint(p, fend, &sz) || (uint64_t)(fend - p) < sz) continue;
    const uint8_t* q = p;
    const uint8_t* qend = p + sz;
    const uint8_t* name = nullptr;
    uint64_t name_len = 0;
    const uint8_t* key = nullptr;
    uint64_t key_len = 0;
    bool ok = true;
    while (q < qend) {
      uint64_t t;
      if (!get_varint(q, qend, &t)) { ok = false; break; }
      uint32_t field = (uint32_t)(t >> 3);
      uint32_t wire = (uint32_t)(t & 7);
      if (wire == 2 && (field == 1 || field == 2)) {
        uint64_t l;
        if (!get_varint(q, qend, &l) || (uint64_t)(qend - q) < l) {
          ok = false;
          break;
        }
        if (field == 1) {
          name = q;
          name_len = l;
        } else {
          key = q;
          key_len = l;
        }
        q += l;
      } else if (!skip_field(q, qend, wire)) {
        ok = false;
        break;
      }
    }
    if (!ok || name_len == 0 || key_len == 0) continue;
    uint64_t h = OFFSET;
    const uint8_t us = '_';
    const uint8_t* parts[3] = {name, &us, key};
    const uint64_t lens[3] = {name_len, 1, key_len};
    if (variant == 0) {
      for (int s = 0; s < 3; s++)
        for (uint64_t j = 0; j < lens[s]; j++) {
          h = h * PRIME;
          h ^= parts[s][j];
        }
    } else {
      for (int s = 0; s < 3; s++)
        for (uint64_t j = 0; j < lens[s]; j++) {
          h ^= parts[s][j];
          h = h * PRIME;
        }
    }
    out[i] = (int64_t)h;
  }
}

// Parse the payload into per-request columns.  err[i]: 0 ok, 1 empty
// unique_key, 2 empty name (matching the service's validation order and
// messages).  hash[i] = XXH64(name + "_" + unique_key) with 0 remapped to 1;
// 0 on errored requests.  name_hash[i] = XXH64(name) with 0 remapped to 1
// (0 when the name is empty) — the columnar route key for name-scoped
// tiers (the sketch tier routes by this the same way the slot table keys
// by the 64-bit request fingerprint).  msg_off/msg_len give each
// RateLimitReq's frame (tag byte + length varint + body) within the
// payload, so a router can splice request bytes verbatim into a
// peer-forward payload without re-encoding.  Returns the parsed count, or
// -1 on malformed input (callers fall back to the python-protobuf path
// for the real error).
int64_t gub_parse_reqs2(const uint8_t* buf, int64_t len, int64_t cap,
                        int64_t* hash, int32_t* err, int64_t* hits,
                        int64_t* limit, int64_t* duration, int32_t* algo,
                        int64_t* behavior, int64_t* burst,
                        int64_t* msg_off, int64_t* msg_len,
                        int64_t* name_hash) {
  const uint8_t* p = buf;
  const uint8_t* end = buf + len;
  int64_t n = 0;
  std::vector<uint8_t> scratch;
  while (p < end) {
    const uint8_t* frame_start = p;
    uint64_t tag;
    if (!get_varint(p, end, &tag)) return -1;
    if ((tag >> 3) != 1 || (tag & 7) != 2) {
      if (!skip_field(p, end, (uint32_t)(tag & 7))) return -1;
      continue;
    }
    uint64_t sz;
    if (!get_varint(p, end, &sz) || (uint64_t)(end - p) < sz) return -1;
    if (n >= cap) return -1;
    const uint8_t* q = p;
    const uint8_t* qend = p + sz;
    p = qend;
    msg_off[n] = (int64_t)(frame_start - buf);
    msg_len[n] = (int64_t)(qend - frame_start);

    const uint8_t* name = nullptr;
    uint64_t name_len = 0;
    const uint8_t* key = nullptr;
    uint64_t key_len = 0;
    int64_t f_hits = 0, f_limit = 0, f_duration = 0, f_behavior = 0,
            f_burst = 0;
    int32_t f_algo = 0;
    while (q < qend) {
      uint64_t t;
      if (!get_varint(q, qend, &t)) return -1;
      uint32_t field = (uint32_t)(t >> 3);
      uint32_t wire = (uint32_t)(t & 7);
      if (wire == 2 && (field == 1 || field == 2)) {
        uint64_t l;
        if (!get_varint(q, qend, &l) || (uint64_t)(qend - q) < l) return -1;
        if (field == 1) {
          name = q;
          name_len = l;
        } else {
          key = q;
          key_len = l;
        }
        q += l;
      } else if (wire == 0 && field >= 3 && field <= 8) {
        uint64_t v;
        if (!get_varint(q, qend, &v)) return -1;
        switch (field) {
          case 3: f_hits = (int64_t)v; break;
          case 4: f_limit = (int64_t)v; break;
          case 5: f_duration = (int64_t)v; break;
          case 6: f_algo = (int32_t)v; break;
          case 7: f_behavior = (int64_t)v; break;
          case 8: f_burst = (int64_t)v; break;
        }
      } else {
        if (!skip_field(q, qend, wire)) return -1;
      }
    }
    hits[n] = f_hits;
    limit[n] = f_limit;
    duration[n] = f_duration;
    algo[n] = f_algo;
    behavior[n] = f_behavior;
    burst[n] = f_burst;
    if (name_len == 0) {
      name_hash[n] = 0;
    } else {
      uint64_t nh = xxh64(name, name_len);
      if (nh == 0) nh = 1;
      name_hash[n] = (int64_t)nh;
    }
    if (key_len == 0) {
      err[n] = 1;
      hash[n] = 0;
    } else if (name_len == 0) {
      err[n] = 2;
      hash[n] = 0;
    } else {
      err[n] = 0;
      scratch.resize(name_len + 1 + key_len);
      std::memcpy(scratch.data(), name, name_len);
      scratch[name_len] = '_';
      std::memcpy(scratch.data() + name_len + 1, key, key_len);
      uint64_t h = xxh64(scratch.data(), scratch.size());
      if (h == 0) h = 1;
      hash[n] = (int64_t)h;
    }
    n++;
  }
  return n;
}

// Parse a GetRateLimitsResp / GetPeerRateLimitsResp payload into response
// columns (status=1 limit=2 remaining=3 reset_time=4 error=5); the router
// uses this to merge peer-forwarded responses back into its output
// columns.  err_off/err_len index INTO the payload (zero len = no error).
// meta_off/meta_len cover the item's metadata map entries (field 6) as
// raw wire frames — tag + length + body — so a forwarder can splice the
// owner's metadata verbatim into its own response.  Serializers write
// map entries contiguously; if an item's entries are fragmented by an
// interleaved field, meta_len is -1 (caller drops the metadata rather
// than splicing unrelated bytes).  Returns the item count, or -1 on
// malformed input.
int64_t gub_parse_resps2(const uint8_t* buf, int64_t len, int64_t cap,
                         int64_t* status, int64_t* limit, int64_t* remaining,
                         int64_t* reset_time, int64_t* err_off,
                         int64_t* err_len, int64_t* meta_off,
                         int64_t* meta_len) {
  const uint8_t* p = buf;
  const uint8_t* end = buf + len;
  int64_t n = 0;
  while (p < end) {
    uint64_t tag;
    if (!get_varint(p, end, &tag)) return -1;
    if ((tag >> 3) != 1 || (tag & 7) != 2) {
      if (!skip_field(p, end, (uint32_t)(tag & 7))) return -1;
      continue;
    }
    uint64_t sz;
    if (!get_varint(p, end, &sz) || (uint64_t)(end - p) < sz) return -1;
    if (n >= cap) return -1;
    const uint8_t* q = p;
    const uint8_t* qend = p + sz;
    p = qend;
    status[n] = limit[n] = remaining[n] = reset_time[n] = 0;
    err_off[n] = err_len[n] = 0;
    meta_off[n] = meta_len[n] = 0;
    const uint8_t* meta_end = nullptr;
    while (q < qend) {
      const uint8_t* field_start = q;
      uint64_t t;
      if (!get_varint(q, qend, &t)) return -1;
      uint32_t field = (uint32_t)(t >> 3);
      uint32_t wire = (uint32_t)(t & 7);
      if (wire == 0 && field >= 1 && field <= 4) {
        uint64_t v;
        if (!get_varint(q, qend, &v)) return -1;
        switch (field) {
          case 1: status[n] = (int64_t)v; break;
          case 2: limit[n] = (int64_t)v; break;
          case 3: remaining[n] = (int64_t)v; break;
          case 4: reset_time[n] = (int64_t)v; break;
        }
      } else if (wire == 2 && field == 5) {
        uint64_t l;
        if (!get_varint(q, qend, &l) || (uint64_t)(qend - q) < l) return -1;
        err_off[n] = (int64_t)(q - buf);
        err_len[n] = (int64_t)l;
        q += l;
      } else if (wire == 2 && field == 6) {
        uint64_t l;
        if (!get_varint(q, qend, &l) || (uint64_t)(qend - q) < l) return -1;
        q += l;
        if (meta_len[n] == 0) {
          meta_off[n] = (int64_t)(field_start - buf);
          meta_len[n] = (int64_t)(q - field_start);
        } else if (meta_len[n] > 0 && field_start == meta_end) {
          meta_len[n] += (int64_t)(q - field_start);
        } else {
          meta_len[n] = -1;  // fragmented — caller drops
        }
        meta_end = q;
      } else {
        if (!skip_field(q, qend, wire)) return -1;
      }
    }
    n++;
  }
  return n;
}

static inline int varint_size(uint64_t v) {
  int s = 1;
  while (v >= 0x80) {
    v >>= 7;
    s++;
  }
  return s;
}

static inline void put_varint(uint8_t*& w, uint64_t v) {
  while (v >= 0x80) {
    *w++ = (uint8_t)(v | 0x80);
    v >>= 7;
  }
  *w++ = (uint8_t)v;
}

// Emit GetRateLimitsResp (or GetPeerRateLimitsResp) bytes from packed
// response columns.  err_blob/err_off carry per-request error strings
// (err_off[i]..err_off[i+1]); zero-length means no error.  meta_blob/
// meta_off (may be null) carry per-request PRE-ENCODED metadata map
// entries — complete field-6 wire frames (tag + length + body), one or
// more per item, copied into the body verbatim.  Callers build frames
// with the python helper (meta_frame) or splice them from a parsed
// response's meta span — this covers the forwarded-response "owner"
// annotation (gubernator.go asyncRequests) and the sketch tier's
// "tier" tag with one mechanism.  Zero-valued fields are omitted like
// proto3 requires.  Returns bytes written, or -1 if `cap` is too small.
int64_t gub_serialize_resps2(int64_t n, const int64_t* status,
                             const int64_t* limit, const int64_t* remaining,
                             const int64_t* reset_time,
                             const uint8_t* err_blob, const int64_t* err_off,
                             const uint8_t* meta_blob,
                             const int64_t* meta_off,
                             uint8_t* out, int64_t cap) {
  uint8_t* w = out;
  uint8_t* wend = out + cap;
  for (int64_t i = 0; i < n; i++) {
    uint64_t elen = (uint64_t)(err_off[i + 1] - err_off[i]);
    uint64_t mlen =
        meta_off ? (uint64_t)(meta_off[i + 1] - meta_off[i]) : 0;
    size_t body = 0;
    if (status[i]) body += 1 + varint_size((uint64_t)status[i]);
    if (limit[i]) body += 1 + varint_size((uint64_t)limit[i]);
    if (remaining[i]) body += 1 + varint_size((uint64_t)remaining[i]);
    if (reset_time[i]) body += 1 + varint_size((uint64_t)reset_time[i]);
    if (elen) body += 1 + varint_size(elen) + elen;
    body += mlen;
    size_t total = 1 + varint_size(body) + body;
    if ((size_t)(wend - w) < total) return -1;
    *w++ = 0x0A;  // field 1, wire 2
    put_varint(w, body);
    if (status[i]) {
      *w++ = 0x08;
      put_varint(w, (uint64_t)status[i]);
    }
    if (limit[i]) {
      *w++ = 0x10;
      put_varint(w, (uint64_t)limit[i]);
    }
    if (remaining[i]) {
      *w++ = 0x18;
      put_varint(w, (uint64_t)remaining[i]);
    }
    if (reset_time[i]) {
      *w++ = 0x20;
      put_varint(w, (uint64_t)reset_time[i]);
    }
    if (elen) {
      *w++ = 0x2A;
      put_varint(w, elen);
      std::memcpy(w, err_blob + err_off[i], elen);
      w += elen;
    }
    if (mlen) {
      std::memcpy(w, meta_blob + meta_off[i], mlen);
      w += mlen;
    }
  }
  return (int64_t)(w - out);
}

// Emit GetRateLimitsReq (or GetPeerRateLimitsReq / LeaseReq.requests —
// all use repeated field 1... field numbering below is the RateLimitReq
// schema) wire bytes from packed request columns — the CLIENT half of
// the codec: a compiled SDK (client.py FastV1Client) serializes a whole
// batch without constructing a single python protobuf object, attacking
// the ~1.3ms of python client machinery the E2E artifacts measure.
//
// name_blob/name_off and key_blob/key_off carry the n strings as
// concatenated bytes with (n+1) offsets (the gub_xxh64_batch layout).
// Numeric columns are int64 (algo included — widened by the caller);
// negative values (hit refunds) encode as 10-byte two's-complement
// varints exactly like protobuf's int64.  Zero-valued fields are
// omitted per proto3.  Returns bytes written, or -1 if `cap` is too
// small.
int64_t gub_serialize_reqs(int64_t n, const uint8_t* name_blob,
                           const int64_t* name_off,
                           const uint8_t* key_blob,
                           const int64_t* key_off, const int64_t* hits,
                           const int64_t* limit, const int64_t* duration,
                           const int64_t* algo, const int64_t* behavior,
                           const int64_t* burst, uint8_t* out,
                           int64_t cap) {
  uint8_t* w = out;
  uint8_t* wend = out + cap;
  for (int64_t i = 0; i < n; i++) {
    uint64_t nlen = (uint64_t)(name_off[i + 1] - name_off[i]);
    uint64_t klen = (uint64_t)(key_off[i + 1] - key_off[i]);
    size_t body = 0;
    if (nlen) body += 1 + varint_size(nlen) + nlen;
    if (klen) body += 1 + varint_size(klen) + klen;
    if (hits[i]) body += 1 + varint_size((uint64_t)hits[i]);
    if (limit[i]) body += 1 + varint_size((uint64_t)limit[i]);
    if (duration[i]) body += 1 + varint_size((uint64_t)duration[i]);
    if (algo[i]) body += 1 + varint_size((uint64_t)algo[i]);
    if (behavior[i]) body += 1 + varint_size((uint64_t)behavior[i]);
    if (burst[i]) body += 1 + varint_size((uint64_t)burst[i]);
    size_t total = 1 + varint_size(body) + body;
    if ((size_t)(wend - w) < total) return -1;
    *w++ = 0x0A;  // field 1 (requests), wire 2
    put_varint(w, body);
    if (nlen) {
      *w++ = 0x0A;  // name = 1
      put_varint(w, nlen);
      std::memcpy(w, name_blob + name_off[i], nlen);
      w += nlen;
    }
    if (klen) {
      *w++ = 0x12;  // unique_key = 2
      put_varint(w, klen);
      std::memcpy(w, key_blob + key_off[i], klen);
      w += klen;
    }
    if (hits[i]) {
      *w++ = 0x18;  // hits = 3
      put_varint(w, (uint64_t)hits[i]);
    }
    if (limit[i]) {
      *w++ = 0x20;  // limit = 4
      put_varint(w, (uint64_t)limit[i]);
    }
    if (duration[i]) {
      *w++ = 0x28;  // duration = 5
      put_varint(w, (uint64_t)duration[i]);
    }
    if (algo[i]) {
      *w++ = 0x30;  // algorithm = 6
      put_varint(w, (uint64_t)algo[i]);
    }
    if (behavior[i]) {
      *w++ = 0x38;  // behavior = 7
      put_varint(w, (uint64_t)behavior[i]);
    }
    if (burst[i]) {
      *w++ = 0x40;  // burst = 8
      put_varint(w, (uint64_t)burst[i]);
    }
  }
  return (int64_t)(w - out);
}

}  // extern "C"
