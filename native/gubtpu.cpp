// gubernator-tpu native host runtime.
//
// The device step is sub-millisecond; at batch_limit-scale traffic the
// host-side request packing (per-key string hashing + duplicate-round
// assignment) dominates when done in Python.  This library provides the two
// hot host ops over raw buffers, exposed via a C ABI for ctypes
// (gubernator_tpu/native/__init__.py):
//
//   gub_xxh64_batch    — XXH64 of N length-prefixed keys (the device
//                        fingerprint; matches python-xxhash seed 0)
//   gub_assign_rounds  — the packer's (round, lane) assignment with
//                        per-(round, shard) lane counters and hash-level
//                        duplicate detection (ops/batch.py's contract:
//                        occurrence k of a key lands in a strictly later
//                        round than occurrence k-1)
//
// Build: make -C native  (g++ -O3 -shared; no external dependencies —
// XXH64 is implemented from its public spec below).

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// XXH64 (from the xxHash spec; seed fixed to 0 like core/hashing.py)
// ---------------------------------------------------------------------------

static const uint64_t P1 = 11400714785074694791ULL;
static const uint64_t P2 = 14029467366897019727ULL;
static const uint64_t P3 = 1609587929392839161ULL;
static const uint64_t P4 = 9650029242287828579ULL;
static const uint64_t P5 = 2870177450012600261ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86/arm64)
}

static inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

static inline uint64_t xxh64_round(uint64_t acc, uint64_t input) {
  acc += input * P2;
  acc = rotl64(acc, 31);
  acc *= P1;
  return acc;
}

static inline uint64_t xxh64_merge(uint64_t acc, uint64_t val) {
  val = xxh64_round(0, val);
  acc ^= val;
  acc = acc * P1 + P4;
  return acc;
}

static uint64_t xxh64(const uint8_t* p, size_t len) {
  const uint8_t* end = p + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = P1 + P2, v2 = P2, v3 = 0, v4 = 0 - P1;
    const uint8_t* limit = end - 32;
    do {
      v1 = xxh64_round(v1, read64(p));
      v2 = xxh64_round(v2, read64(p + 8));
      v3 = xxh64_round(v3, read64(p + 16));
      v4 = xxh64_round(v4, read64(p + 24));
      p += 32;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = xxh64_merge(h, v1);
    h = xxh64_merge(h, v2);
    h = xxh64_merge(h, v3);
    h = xxh64_merge(h, v4);
  } else {
    h = P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    h ^= xxh64_round(0, read64(p));
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= (uint64_t)read32(p) * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * P5;
    h = rotl64(h, 11) * P1;
    p++;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

// Hash n keys packed as a concatenated blob with (n+1) byte offsets.
// out[i] = xxh64(blob[offsets[i]:offsets[i+1]]), remapped 0 -> 1 (the
// empty-slot sentinel rule, core/hashing.py key_hash64).
void gub_xxh64_batch(const uint8_t* blob, const int64_t* offsets, int64_t n,
                     int64_t* out) {
  for (int64_t i = 0; i < n; i++) {
    uint64_t h =
        xxh64(blob + offsets[i], (size_t)(offsets[i + 1] - offsets[i]));
    if (h == 0) h = 1;
    out[i] = (int64_t)h;
  }
}

// ---------------------------------------------------------------------------
// Round/lane assignment (ops/batch.py pack_requests_grid inner loop)
// ---------------------------------------------------------------------------

// Open-addressing map from key hash -> last assigned round (linear probe).
struct RoundMap {
  std::vector<uint64_t> keys;
  std::vector<int32_t> last_round;
  uint64_t mask;
  explicit RoundMap(int64_t n) {
    uint64_t cap = 16;
    while (cap < (uint64_t)n * 2) cap <<= 1;
    keys.assign(cap, 0);
    last_round.assign(cap, -1);
    mask = cap - 1;
  }
  int32_t* slot(uint64_t h) {
    uint64_t i = (h * P1) & mask;
    while (keys[i] != 0 && keys[i] != h) i = (i + 1) & mask;
    keys[i] = h;
    return &last_round[i];
  }
};

// Assign each request a (round, lane) such that:
//  - a key hash appears at most once per round,
//  - occurrence k of a key lands in a strictly later round than k-1,
//  - each (round, shard) holds at most batch_size lanes.
// hashes[i] == 0 marks an errored request (skipped; round=-1).
// Returns the number of rounds.
int64_t gub_assign_rounds(const int64_t* hashes, const int32_t* shards,
                          int64_t n, int32_t n_shards, int32_t batch_size,
                          int32_t* out_round, int32_t* out_lane) {
  RoundMap seen(n);
  // counters[r * n_shards + s] = lanes used; keysets per round for the
  // "key not in round" check are implied by last_round tracking: a key's
  // next occurrence starts probing at last_round+1, and WITHIN one probe
  // sequence only capacity can force extra rounds, never the same key.
  std::vector<int32_t> counters;
  int64_t n_rounds = 0;
  for (int64_t i = 0; i < n; i++) {
    uint64_t h = (uint64_t)hashes[i];
    if (h == 0) {
      out_round[i] = -1;
      out_lane[i] = -1;
      continue;
    }
    int32_t s = shards ? shards[i] : 0;
    int32_t* lr = seen.slot(h);
    int32_t r = *lr + 1;
    for (;;) {
      if (r >= n_rounds) {
        counters.resize((size_t)(r + 1) * n_shards, 0);
        n_rounds = r + 1;
      }
      int32_t& c = counters[(size_t)r * n_shards + s];
      if (c < batch_size) {
        out_round[i] = r;
        out_lane[i] = c;
        c++;
        *lr = r;
        break;
      }
      r++;
    }
  }
  return n_rounds;
}

}  // extern "C"
