"""host-sync: device->host fetches only inside the executor module set.

One stray host fetch on a serving path costs a full device round-trip
(70-300ms through the TPU tunnel — every BENCH_E2E artifact is dominated
by fetch count).  The single-writer executor modules are the ONLY code
allowed to call the synchronizing primitives:

  jax.device_get(...)        explicit device->host copy
  <x>.block_until_ready()    dispatch barrier
  np.asarray(...)            implicit copy when handed a device array
  jnp.ndarray.item() / float(arr[i])-style scalar reads on subscripts

Everything else (net/, discovery/, daemon, the object-path service)
must hand work to the executor and consume its host-side results.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from tools.gubguard.core import Checker, Finding, ModuleInfo, dotted_name

# Modules that ARE the executor / host-transfer layer.  Matching is by
# posix-relpath suffix so the checker works from any scan root.
ALLOWED_SUFFIXES = (
    "runtime/backend.py",
    "runtime/fastpath.py",
    # The ring runner thread IS the fetch side of the response ring —
    # the one place ring-mode device->host syncs are supposed to live
    # (docs/ring.md; the request path stays fetch-free).
    "runtime/ring.py",
    # The gubstat sampler fetches census leaves on the executor thread
    # (host-job submit + run_in_executor), and the tenant ledger only
    # regroups arrays the fast lane already fetched — its np.asarray
    # calls are host->host (docs/observability.md).
    "runtime/gubstat.py",
    "runtime/checkpoint.py",
    # The tier manager's fetches run on its own worker thread through
    # the ring's host-job lane (docs/tiering.md), and the cold store
    # itself is pure host numpy — its np.asarray calls are host->host;
    # the request-path touch (note_access) is a set probe, no device
    # arrays in reach.
    "runtime/coldtier.py",
    "runtime/sketch_backend.py",
    "runtime/store.py",
    "parallel/sharded.py",
    "parallel/global_sync.py",
    "parallel/mesh.py",
    # Device-layer kernels and their host packers.
    "ops/",
    # Tooling / harnesses, not serving paths.
    "testing/",
    "cli/",
)

_SYNC_CALLS = {"jax.device_get", "np.asarray", "numpy.asarray"}


def _allowed(relpath: str) -> bool:
    for suf in ALLOWED_SUFFIXES:
        if suf.endswith("/"):
            if ("/" + relpath).find("/" + suf) != -1 or relpath.startswith(
                suf
            ):
                return True
        elif relpath.endswith(suf):
            return True
    return False


class HostSyncChecker(Checker):
    name = "host-sync"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        if _allowed(mod.relpath):
            return ()
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            msg = self._classify(node)
            if msg:
                out.append(Finding(
                    checker=self.name, path=mod.relpath,
                    line=node.lineno, message=msg,
                ))
        return out

    @staticmethod
    def _classify(call: ast.Call) -> str:
        fn = call.func
        dn = dotted_name(fn)
        if dn in _SYNC_CALLS:
            return (
                f"'{dn}' is a device->host fetch; only the executor "
                "module set may synchronize (one fetch costs a full "
                "device round-trip on a serving path)"
            )
        if isinstance(fn, ast.Attribute) and fn.attr == "block_until_ready":
            return (
                "'.block_until_ready()' is a dispatch barrier; only the "
                "executor module set may synchronize"
            )
        if (
            isinstance(fn, ast.Name)
            and fn.id in ("float", "int", "bool")
            and len(call.args) == 1
            and isinstance(call.args[0], ast.Subscript)
        ):
            sub = call.args[0]
            # Array-style indexing only: `x[i]` / `x[0]` on a simple
            # receiver.  String keys, slices, and call results are
            # dict/str/tuple access, not device-array element reads.
            idx = sub.slice
            arrayish = (
                isinstance(sub.value, (ast.Name, ast.Attribute))
                and (
                    isinstance(idx, ast.Name)
                    or (
                        isinstance(idx, ast.Constant)
                        and isinstance(idx.value, int)
                    )
                )
            )
            if arrayish:
                return (
                    f"'{fn.id}(x[i])' concretizes one element; if x is "
                    "a device array this is a per-element host fetch — "
                    "batch the read in an executor module instead"
                )
        return ""
