"""gubguard: project-specific static analysis for gubernator-tpu.

Enforces the fast-lane invariants (docs/invariants.md) that the code
otherwise carries only as convention: host-fetch containment, a
non-blocking event loop, one global lock order, jit purity, GUBER_*
env parity, and time-unit suffix discipline.  Run as:

    python -m tools.gubguard gubernator_tpu/

Exit status 0 = clean (warnings allowed), 1 = errors (or warnings under
--strict).  The runtime counterpart is the raceguard pytest plugin
(gubernator_tpu/testing/raceguard.py).
"""
from __future__ import annotations

from pathlib import Path
from typing import List, Optional, Sequence

from tools.gubguard.blocking import BlockingChecker
from tools.gubguard.core import Checker, Finding, run_checkers
from tools.gubguard.envparity import EnvParityChecker
from tools.gubguard.hostsync import HostSyncChecker
from tools.gubguard.jitpurity import JitPurityChecker
from tools.gubguard.lockcomplete import LockCompleteChecker
from tools.gubguard.lockorder import LockOrderChecker
from tools.gubguard.unitsuffix import UnitSuffixChecker

ALL_CHECKERS = (
    "host-sync",
    "async-blocking",
    "lock-order",
    "lock-complete",
    "jit-purity",
    "env-parity",
    "unit-suffix",
)


def make_checkers(select: Optional[Sequence[str]] = None) -> List[Checker]:
    factory = {
        "host-sync": HostSyncChecker,
        "async-blocking": BlockingChecker,
        "lock-order": LockOrderChecker,
        "lock-complete": LockCompleteChecker,
        "jit-purity": JitPurityChecker,
        "env-parity": EnvParityChecker,
        "unit-suffix": UnitSuffixChecker,
    }
    names = list(select) if select else list(ALL_CHECKERS)
    unknown = [n for n in names if n not in factory]
    if unknown:
        raise ValueError(f"unknown checkers: {unknown}")
    return [factory[n]() for n in names]


def run(
    paths: Sequence[str],
    select: Optional[Sequence[str]] = None,
    root: Optional[Path] = None,
) -> List[Finding]:
    """Run the selected checkers over `paths`; returns sorted findings."""
    return run_checkers(
        [Path(p) for p in paths], make_checkers(select), root=root
    )
