"""lock-order: one global lock acquisition order, machine-checked.

The discipline documented at parallel/global_sync.py ("Lock order
everywhere: auth (backend) before cache (self)") generalizes to a single
global ranking; any two code paths that nest the same pair of locks in
opposite orders can deadlock under concurrency (the classic inversion a
race detector exists to catch).

The checker extracts every lexically nested acquisition site —
`with a._lock, b._lock:` items and `with` statements nested inside other
`with` statements, sync or async — canonicalizes each lock expression to
a lock CLASS, then verifies:

  1. no pair of lock classes is acquired in both orders anywhere;
  2. the merged acquisition graph is acyclic;
  3. edges between RANKED locks respect the declared global order:
       backend._keymap_lock < backend._lock < engine._lock
                            < sketch._lock  < store._lock
  4. no nested re-acquisition of the same (non-reentrant) lock class.

Canonicalization: `self._lock` resolves through the enclosing class
(DeviceBackend/MeshBackend -> backend._lock, GlobalEngine ->
engine._lock, ...); `self.b._lock` / `backend._lock` resolve through the
receiver variable name.
"""
from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

from tools.gubguard.core import Checker, Finding, ModuleInfo, dotted_name

# (enclosing class, attribute) -> canonical lock class
CLASS_LOCK_MAP = {
    ("PersistenceHost", "_lock"): "backend._lock",
    ("DeviceBackend", "_lock"): "backend._lock",
    ("MeshBackend", "_lock"): "backend._lock",
    ("PersistenceHost", "_keymap_lock"): "backend._keymap_lock",
    ("DeviceBackend", "_keymap_lock"): "backend._keymap_lock",
    ("MeshBackend", "_keymap_lock"): "backend._keymap_lock",
    ("GlobalEngine", "_lock"): "engine._lock",
    ("SketchBackend", "_lock"): "sketch._lock",
    ("Store", "_lock"): "store._lock",
    ("MockStore", "_lock"): "store._lock",
    ("HotKeyTracker", "_lock"): "hotkey._lock",
    ("LeaseManager", "_lock"): "lease._lock",
    ("_LeaseTable", "_lock"): "lease.client._lock",
    ("ReshardManager", "_lock"): "reshard._lock",
    ("RegionManager", "_lock"): "multiregion._lock",
    ("ColdTier", "_lock"): "coldtier._lock",
    ("TenantAccounting", "_lock"): "gubstat._lock",
    ("HdrRecorder", "_lock"): "loadgen.hdr._lock",
    ("FlightRecorder", "_lock"): "flightrec._lock",
    ("_TraceState", "_lock"): "tracing._lock",
    ("MemorySpanExporter", "_lock"): "tracing.exporter._lock",
    ("SketchBackend", "_compile_lock"): "sketch._compile_lock",
    ("SketchBackend", "_spill_lock"): "sketch._spill_lock",
    ("Clock", "_lock"): "clock._lock",
    ("Daemon", "_set_peers_lock"): "daemon._set_peers_lock",
    ("Service", "_peer_lock"): "service._peer_lock",
    ("PeerClient", "_connect_lock"): "peer_client._connect_lock",
}
# receiver variable name -> canonical prefix
VAR_ALIAS = {
    "b": "backend",
    "backend": "backend",
    "be": "backend",
    "engine": "engine",
    "eng": "engine",
    "sketch": "sketch",
    "sb": "sketch",
    "store": "store",
    "hotkeys": "hotkey",
    "hk": "hotkey",
    "leases": "lease",
    "lm": "lease",
    "flightrec": "flightrec",
    "fr": "flightrec",
    "tenants": "gubstat",
    "ta": "gubstat",
    "cold": "coldtier",
    "coldtier": "coldtier",
    "ct": "coldtier",
    "regions": "multiregion",
    "rm": "multiregion",
}
# Declared global acquisition order (lower rank acquired first).
# flightrec._lock ranks LAST: any layer may record into the flight
# recorder while holding its own lock (e.g. under backend._lock in a
# drain), and the recorder never takes another lock while holding its own.
#
# The fast lane's pipelined-drain stage slots (_Coalescer._dispatch_sem /
# _fetch / _overlap, runtime/fastpath.py) are asyncio SEMAPHORES acquired
# on the event loop, ranked BEFORE every thread lock here: a drain takes
# fetch slot -> dispatch slot -> (on a pool thread) backend._lock, and
# nothing acquires a stage slot while holding a thread lock.  They are
# declared for the record; the lexical checker only sees `with` blocks
# over *_lock attributes, and raceguard's runtime graph covers
# asyncio.Lock — a future conversion of these slots to locks must keep
# this order.
RANK = {
    "coalescer._fetch_slot": 1,
    "coalescer._dispatch_slot": 2,
    # The event-loop asyncio.Locks rank with the coalescer slots,
    # BEFORE every thread lock: each is acquired on the loop while
    # holding no thread lock, and any thread lock taken inside runs on
    # a pool thread or in a short critical section entered afterwards.
    # set_peers flows Daemon -> Service, so the daemon's lock ranks
    # first; the peer-client connect gate is a leaf among them.
    "daemon._set_peers_lock": 3,
    "service._peer_lock": 4,
    "peer_client._connect_lock": 5,
    "backend._keymap_lock": 10,
    "backend._lock": 20,
    "engine._lock": 30,
    # sketch._compile_lock serializes first-compile of a new batch
    # shape against a throwaway state, deliberately OUTSIDE the
    # dispatch lock (sketch._lock) — callers fetch the compiled step
    # before taking _lock, so compile ranks before dispatch.
    "sketch._compile_lock": 39,
    "sketch._lock": 40,
    # sketch._spill_lock guards the dynamic-name spillover set; taken
    # alone from the pressure-report path, never nested with dispatch.
    "sketch._spill_lock": 41,
    "store._lock": 50,
    # coldtier._lock (runtime/coldtier.py cold-store rows + member
    # set) is a leaf taken alone: the request path's note_access probes
    # membership holding nothing, the tier worker's put/pop run between
    # (never across) device dispatches, and the store takes no other
    # lock while held.  Ranked before the routing-plane tails so a
    # future caller holding it cannot legally take backend/engine locks.
    "coldtier._lock": 54,
    # hotkey._lock (runtime/hotkey.py window/hot-set state) is acquired
    # from routing paths holding nothing and takes nothing while held
    # (pressure_fn reads lock-free peer/flightrec attrs; flight-recorder
    # records fire after release) — ranked just before the
    # record-anything tail locks.
    "hotkey._lock": 55,
    # lease._lock (runtime/lease.py holder dicts) sits with hotkey: it
    # is taken from grant/reconcile paths holding nothing, guards only
    # dict state, and is NEVER held across an await or device work (the
    # carve rides _check_local outside it).  The client-side twin
    # (lease.client._lock, client._LeaseTable) has the same contract.
    "lease._lock": 56,
    "lease.client._lock": 57,
    # reshard._lock (runtime/reshard.py handoff dicts) follows the
    # lease contract exactly: taken from remap/handoff paths holding
    # nothing, guards only dict state, never held across an await or
    # any device work (extraction/injection ride the device executor
    # outside it).
    "reshard._lock": 58,
    # multiregion._lock (runtime/multiregion.py burn ledger / carve
    # reset memory / drift counter) follows the reshard contract:
    # taken from the serve/flush/cutover paths and the gubstat census
    # (carve_slot_keys) holding nothing, never held across an await or
    # device work (carve checks ride _check_local outside it), and
    # takes nothing while held (drift gauge updates happen after
    # release).
    "multiregion._lock": 58.5,
    # gubstat._lock (runtime/gubstat.py tenant ledger) is a leaf: taken
    # from the _check_local tail (event loop) and fast-lane fetch
    # threads while holding nothing, guards only dict/CMS state, and
    # takes nothing while held (name decode closures touch no locks).
    "gubstat._lock": 59,
    "flightrec._lock": 60,
    # loadgen.hdr._lock (runtime/metrics.py HdrRecorder bucket counts)
    # is a leaf: record()/percentile()/merge() guard only the counts
    # dict and take nothing while held — merge() snapshots the OTHER
    # recorder's counts under its lock FIRST, releases, then takes its
    # own, so two merges never hold both locks at once.
    "loadgen.hdr._lock": 62,
    # tracing._lock (runtime/tracing.py counters/recent ring) ranks with
    # flightrec: span bookkeeping may run under ANY layer's lock (a span
    # ends inside a locked merge), and the tracing plane never takes
    # another lock while holding its own (exports run outside it).
    "tracing._lock": 70,
    "tracing.exporter._lock": 71,
    # clock._lock (core/clock.py frozen-time guard) ranks dead last:
    # now_ns() may be called under ANY other lock (timestamps are
    # taken everywhere), the critical section is two loads, and the
    # clock takes nothing while held.
    "clock._lock": 80,
}

Site = Tuple[str, int]  # (relpath, line)


def _is_lockish(attr: str) -> bool:
    return attr == "lock" or attr.endswith("_lock") or attr.endswith("lock_")


class _LockVisitor(ast.NodeVisitor):
    def __init__(self, checker: "LockOrderChecker", mod: ModuleInfo) -> None:
        self.checker = checker
        self.mod = mod
        self.class_stack: List[str] = []
        self.held: List[Tuple[str, int]] = []  # (canonical, line)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # A new function body starts with no lexically held locks (a
        # callee acquiring under a caller's lock is runtime raceguard's
        # job, not a lexical fact).
        saved, self.held = self.held, []
        self.generic_visit(node)
        self.held = saved

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def _canonical(self, expr: ast.AST) -> Optional[str]:
        dn = dotted_name(expr)
        if dn is None:
            return None
        parts = dn.split(".")
        attr = parts[-1]
        if not _is_lockish(attr):
            return None
        recv = parts[:-1]
        if recv == ["self"] or not recv:
            cls = self.class_stack[-1] if self.class_stack else "<module>"
            return CLASS_LOCK_MAP.get((cls, attr), f"{cls}.{attr}")
        base = recv[-1] if recv[-1] != "self" else (
            recv[-2] if len(recv) > 1 else "self"
        )
        if recv[0] == "self" and len(recv) > 1:
            base = recv[1]
        prefix = VAR_ALIAS.get(base, base)
        return CLASS_LOCK_MAP.get((prefix, attr), f"{prefix}.{attr}")

    def _visit_with(self, node) -> None:
        acquired: List[Tuple[str, int]] = []
        for item in node.items:
            canon = self._canonical(item.context_expr)
            if canon is None:
                continue
            if self.mod.suppressed(node.lineno, self.checker.name):
                continue
            site: Site = (self.mod.relpath, node.lineno)
            for held, _hl in self.held + acquired:
                self.checker.record_edge(held, canon, site)
            acquired.append((canon, node.lineno))
        self.held.extend(acquired)
        for child in node.body:
            self.visit(child)
        if acquired:
            del self.held[-len(acquired):]

    visit_With = _visit_with
    visit_AsyncWith = _visit_with


class LockOrderChecker(Checker):
    name = "lock-order"

    def __init__(self) -> None:
        # (held, acquired) -> first observed site
        self.edges: Dict[Tuple[str, str], Site] = {}

    def record_edge(self, held: str, acquired: str, site: Site) -> None:
        self.edges.setdefault((held, acquired), site)

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        _LockVisitor(self, mod).visit(mod.tree)
        return ()

    def finalize(self, root: Path) -> Iterable[Finding]:
        out: List[Finding] = []
        for (a, b), (path, line) in sorted(self.edges.items()):
            if a == b:
                out.append(Finding(
                    checker=self.name, path=path, line=line,
                    message=(
                        f"nested re-acquisition of '{a}' — "
                        "deadlock on a non-reentrant lock"
                    ),
                ))
                continue
            if (b, a) in self.edges:
                op, ol = self.edges[(b, a)]
                out.append(Finding(
                    checker=self.name, path=path, line=line,
                    message=(
                        f"lock-order inversion: '{a}' -> '{b}' here but "
                        f"'{b}' -> '{a}' at {op}:{ol}"
                    ),
                ))
            ra, rb = RANK.get(a), RANK.get(b)
            if ra is not None and rb is not None and ra > rb:
                out.append(Finding(
                    checker=self.name, path=path, line=line,
                    message=(
                        f"'{a}' acquired before '{b}' violates the "
                        "declared global order (see docs/invariants.md): "
                        + " < ".join(sorted(RANK, key=RANK.get))
                    ),
                ))
        out.extend(self._cycles())
        return out

    def _cycles(self) -> List[Finding]:
        graph: Dict[str, List[str]] = {}
        for (a, b) in self.edges:
            if a != b and (b, a) not in self.edges:
                graph.setdefault(a, []).append(b)
        # Iterative DFS cycle detection (2-cycles already reported above).
        WHITE, GREY, BLACK = 0, 1, 2
        color = {n: WHITE for n in graph}
        out: List[Finding] = []

        def dfs(start: str) -> Optional[List[str]]:
            stack: List[Tuple[str, Iterable[str]]] = [
                (start, iter(graph.get(start, ())))
            ]
            path = [start]
            color[start] = GREY
            while stack:
                node, it = stack[-1]
                nxt = next(it, None)
                if nxt is None:
                    color[node] = BLACK
                    stack.pop()
                    path.pop()
                    continue
                c = color.get(nxt, WHITE)
                if c == GREY:
                    return path[path.index(nxt):] + [nxt]
                if c == WHITE:
                    color[nxt] = GREY
                    path.append(nxt)
                    stack.append((nxt, iter(graph.get(nxt, ()))))
            return None

        for n in list(graph):
            if color.get(n, WHITE) == WHITE:
                cyc = dfs(n)
                if cyc:
                    site = self.edges.get((cyc[0], cyc[1]), ("<graph>", 0))
                    out.append(Finding(
                        checker=self.name, path=site[0], line=site[1],
                        message=(
                            "lock acquisition cycle: "
                            + " -> ".join(cyc)
                        ),
                    ))
                    break
        return out
