"""CLI: python -m tools.gubguard [paths...] [--select a,b] [--strict]."""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tools.gubguard import ALL_CHECKERS, run


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="gubguard",
        description=(
            "Static analysis for gubernator-tpu's fast-lane invariants "
            "(see docs/invariants.md)."
        ),
    )
    ap.add_argument(
        "paths", nargs="*", default=["gubernator_tpu/"],
        help="files or directories to scan (default: gubernator_tpu/)",
    )
    ap.add_argument(
        "--select", metavar="NAMES",
        help="comma-separated checker subset of: " + ", ".join(ALL_CHECKERS),
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="treat warnings as errors",
    )
    ap.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings as a JSON array",
    )
    ap.add_argument(
        "--root", default=".",
        help="repo root for docs/deploy scanning (default: cwd)",
    )
    args = ap.parse_args(argv)

    select = (
        [s.strip() for s in args.select.split(",") if s.strip()]
        if args.select else None
    )
    findings = run(args.paths, select=select, root=Path(args.root))

    if args.as_json:
        print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
    errors = [
        f for f in findings
        if f.severity == "error" or (args.strict and f.severity == "warning")
    ]
    warnings = [f for f in findings if f.severity == "warning"]
    if not args.as_json:
        print(
            f"gubguard: {len(errors)} error(s), "
            f"{len(warnings)} warning(s)"
        )
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
