"""gubguard core: finding model, pragma handling, module loading, runner.

The checkers enforce the fast-lane invariants that are otherwise only
convention (docs/invariants.md):

  host-sync       device->host fetches only inside the executor module set
  async-blocking  no blocking calls on the event loop
  lock-order      one global lock acquisition order
  jit-purity      no wall-clock reads / tracer leaks in jitted code
  env-parity      GUBER_* env surface matches docs + the reference set
  unit-suffix     _ns/_ms/_s time-name suffixes tell the truth

A finding is suppressed by a pragma comment on the flagged line or the
line directly above it:

    x = np.asarray(dev)  # gubguard: ok
    # gubguard: ok=host-sync,jit-purity
    y = float(arr[0])

`ok` alone silences every checker for that line; `ok=<names>` silences
only the named checkers.
"""
from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set

_PRAGMA_RE = re.compile(r"#\s*gubguard:\s*ok(?:=(?P<names>[\w,\-]+))?")


@dataclass(frozen=True)
class Finding:
    checker: str
    path: str  # repo-relative posix path
    line: int
    message: str
    severity: str = "error"  # "error" | "warning"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: [{self.checker}] "
            f"{self.severity}: {self.message}"
        )


@dataclass
class ModuleInfo:
    """One parsed python module handed to every checker."""

    path: Path
    relpath: str  # posix, relative to the scan root
    source: str
    tree: ast.Module
    # line -> set of checker names suppressed there ("*" = all)
    pragmas: Dict[int, Set[str]] = field(default_factory=dict)

    def suppressed(self, line: int, checker: str) -> bool:
        for ln in (line, line - 1):
            names = self.pragmas.get(ln)
            if names and ("*" in names or checker in names):
                return True
        return False


def _collect_pragmas(source: str) -> Dict[int, Set[str]]:
    pragmas: Dict[int, Set[str]] = {}
    try:
        toks = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                continue
            names = m.group("names")
            pragmas[tok.start[0]] = (
                set(n.strip() for n in names.split(",") if n.strip())
                if names else {"*"}
            )
    except tokenize.TokenError:
        pass
    return pragmas


def load_module(path: Path, root: Path) -> Optional[ModuleInfo]:
    try:
        source = path.read_text(encoding="utf-8")
        tree = ast.parse(source, filename=str(path))
    except (OSError, SyntaxError, UnicodeDecodeError):
        return None
    try:
        rel = path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        rel = path.as_posix()
    return ModuleInfo(
        path=path, relpath=rel, source=source, tree=tree,
        pragmas=_collect_pragmas(source),
    )


def iter_py_files(paths: Sequence[Path]) -> Iterable[Path]:
    for p in paths:
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


def dotted_name(node: ast.AST) -> Optional[str]:
    """`a.b.c` attribute/name chain as a string, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class Checker:
    """Base checker.  `check_module` runs per file; `finalize` runs once
    after every file has been visited (cross-module checks)."""

    name = "base"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        return ()

    def finalize(self, root: Path) -> Iterable[Finding]:
        return ()


def run_checkers(
    paths: Sequence[Path],
    checkers: Sequence[Checker],
    root: Optional[Path] = None,
) -> List[Finding]:
    root = root or Path.cwd()
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        mod = load_module(path, root)
        if mod is None:
            continue
        for ch in checkers:
            for f in ch.check_module(mod):
                if not mod.suppressed(f.line, ch.name):
                    findings.append(f)
    for ch in checkers:
        findings.extend(ch.finalize(root))
    findings.sort(key=lambda f: (f.path, f.line, f.checker))
    return findings
