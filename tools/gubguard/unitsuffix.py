"""unit-suffix: host-side time values must name their unit truthfully.

The device planes get dimensional checking from gubrange's jaxpr taint
(tools/gubrange/units.py); host code gets this AST pass.  The repo's
convention is that a time-valued name carries its unit as a suffix —
``_ns`` / ``_us`` / ``_ms`` / ``_s`` — and the checker enforces that the
suffix, when present, is TRUE:

  * an assignment to a suffixed name (or attribute) whose right-hand
    side provably carries a different unit is an error
    (``now_ms = time.time()`` stores seconds in a millisecond name);
  * adding, subtracting or comparing two operands with different
    provable units is an error (``deadline_ms - start_ns``);
  * a ``return`` inside a function whose own name is suffixed must not
    provably return a different unit (``def elapsed_ms(): return
    time.monotonic() - t0``).

Unsuffixed scratch names (``t0``, ``start``, ``deadline``) stay legal —
the discipline is "if you name the unit, name it right", which is what
keeps the pass adoptable without a tree-wide rename.  Units are
inferred only where provable: the stdlib wall-clock sources
(``time.time``/``monotonic``/``perf_counter`` → s, their ``_ns``
variants → ns), calls whose terminal name is itself suffixed
(``_now_ms()`` → ms), the repo clock seam (``millisecond_now`` → ms,
``now_ns`` → ns), and decimal rescaling by 1e3/1e6/1e9 which shifts the
unit (``time.time() * 1000`` → ms).  Anything else is unit-unknown and
never flagged.  Waive with ``# gubguard: ok=unit-suffix``.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from tools.gubguard.core import Checker, Finding, ModuleInfo, dotted_name

# Finest-to-coarsest; rescaling moves along this ladder.
_LADDER = ("s", "ms", "us", "ns")

# Exact dotted wall-clock sources (the stdlib time module).
_CALL_UNITS = {
    "time.time": "s",
    "time.monotonic": "s",
    "time.perf_counter": "s",
    "time.time_ns": "ns",
    "time.monotonic_ns": "ns",
    "time.perf_counter_ns": "ns",
    "time.clock_gettime_ns": "ns",
}

# The repo's clock seam (core/clock.py): unit-bearing names without a
# literal suffix.
_TERMINAL_UNITS = {
    "millisecond_now": "ms",
    "time_ns": "ns",
    "monotonic_ns": "ns",
    "perf_counter_ns": "ns",
}

# Numeric factors that shift the ladder by whole steps.
_SCALES = {
    1000: 1, 1000.0: 1, 1e3: 1,
    1000000: 2, 1000000.0: 2, 1e6: 2,
    1000000000: 3, 1000000000.0: 3, 1e9: 3,
}

# Wrappers transparent to units.
_TRANSPARENT_CALLS = {"int", "float", "abs", "round"}


def name_unit(ident: str) -> Optional[str]:
    """The unit a bare identifier claims via its suffix, if any."""
    for suf, unit in (("_ns", "ns"), ("_us", "us"), ("_ms", "ms"),
                      ("_s", "s")):
        if ident.endswith(suf):
            return unit
    return None


def _terminal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _shift(unit: Optional[str], steps: int) -> Optional[str]:
    if unit is None:
        return None
    i = _LADDER.index(unit) + steps
    return _LADDER[i] if 0 <= i < len(_LADDER) else None


def _const_scale(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(
        node.value, (int, float)
    ):
        return _SCALES.get(node.value)
    return None


def infer_unit(node: ast.AST) -> Optional[str]:
    """Best-effort provable unit of an expression; None = unknown."""
    if isinstance(node, (ast.Name, ast.Attribute)):
        term = _terminal(node)
        return name_unit(term) if term else None
    if isinstance(node, ast.Call):
        dotted = dotted_name(node.func)
        if dotted in _CALL_UNITS:
            return _CALL_UNITS[dotted]
        term = _terminal(node.func)
        if term in _TERMINAL_UNITS:
            return _TERMINAL_UNITS[term]
        if term in _TRANSPARENT_CALLS and len(node.args) == 1:
            return infer_unit(node.args[0])
        if term in ("max", "min"):
            units = {infer_unit(a) for a in node.args} - {None}
            return units.pop() if len(units) == 1 else None
        return name_unit(term) if term else None
    if isinstance(node, ast.UnaryOp):
        return infer_unit(node.operand)
    if isinstance(node, ast.BinOp):
        left, right = infer_unit(node.left), infer_unit(node.right)
        if isinstance(node.op, (ast.Mult, ast.Div, ast.FloorDiv)):
            down = isinstance(node.op, ast.Mult)
            scale = _const_scale(node.right)
            if scale is not None and left is not None:
                return _shift(left, scale if down else -scale)
            if isinstance(node.op, ast.Mult):
                scale = _const_scale(node.left)
                if scale is not None and right is not None:
                    return _shift(right, scale)
            return None
        if isinstance(node.op, (ast.Add, ast.Sub)):
            if left is not None and right is not None:
                return left if left == right else None
            return left if left is not None else right
        if isinstance(node.op, ast.Mod):
            return infer_unit(node.left)
    if isinstance(node, ast.IfExp):
        body, orelse = infer_unit(node.body), infer_unit(node.orelse)
        if body is not None and orelse is not None:
            return body if body == orelse else None
        return body if body is not None else orelse
    return None


class _Visitor(ast.NodeVisitor):
    def __init__(self, mod: ModuleInfo, checker_name: str) -> None:
        self.mod = mod
        self.checker = checker_name
        self.findings: List[Finding] = []
        self._fn_units: List[Optional[str]] = []

    def _err(self, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(
            checker=self.checker, path=self.mod.relpath,
            line=getattr(node, "lineno", 1), message=message,
        ))

    # -- rule 1: suffixed targets must receive their own unit ------------

    def _check_store(self, target: ast.AST, value: ast.AST) -> None:
        term = _terminal(target)
        if term is None:
            return
        claimed = name_unit(term)
        if claimed is None:
            return
        actual = infer_unit(value)
        if actual is not None and actual != claimed:
            self._err(target, (
                f"'{term}' claims {claimed} but is assigned a value "
                f"in {actual} — rename the target or convert the value"
            ))

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            if isinstance(tgt, ast.Tuple) and isinstance(
                node.value, ast.Tuple
            ) and len(tgt.elts) == len(node.value.elts):
                for t, v in zip(tgt.elts, node.value.elts):
                    self._check_store(t, v)
            else:
                self._check_store(tgt, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check_store(node.target, node.value)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if isinstance(node.op, (ast.Add, ast.Sub)):
            self._check_store(node.target, node.value)
        self.generic_visit(node)

    # -- rule 2: no cross-unit add/sub/compare ---------------------------

    def _check_mix(self, node: ast.AST, a: ast.AST, b: ast.AST,
                   what: str) -> None:
        ua, ub = infer_unit(a), infer_unit(b)
        if ua is not None and ub is not None and ua != ub:
            self._err(node, (
                f"{what} mixes {ua} and {ub} operands — convert one "
                "side explicitly"
            ))

    def visit_BinOp(self, node: ast.BinOp) -> None:
        if isinstance(node.op, (ast.Add, ast.Sub)):
            self._check_mix(node, node.left, node.right,
                            "addition" if isinstance(node.op, ast.Add)
                            else "subtraction")
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        operands = [node.left, *node.comparators]
        for a, b in zip(operands, operands[1:]):
            self._check_mix(node, a, b, "comparison")
        self.generic_visit(node)

    # -- rule 3: suffixed functions must return their own unit -----------

    def _visit_fn(self, node) -> None:
        self._fn_units.append(name_unit(node.name))
        self.generic_visit(node)
        self._fn_units.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._fn_units.append(None)
        self.generic_visit(node)
        self._fn_units.pop()

    def visit_Return(self, node: ast.Return) -> None:
        claimed = self._fn_units[-1] if self._fn_units else None
        if claimed is not None and node.value is not None:
            actual = infer_unit(node.value)
            if actual is not None and actual != claimed:
                self._err(node, (
                    f"function suffixed {claimed} returns a value in "
                    f"{actual} — convert before returning"
                ))
        self.generic_visit(node)


class UnitSuffixChecker(Checker):
    name = "unit-suffix"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        v = _Visitor(mod, self.name)
        v.visit(mod.tree)
        return v.findings
