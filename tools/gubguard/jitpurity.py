"""jit-purity: no wall-clock reads or tracer leaks in jitted code.

Every function handed to `jax.jit` / `shard_map` is traced ONCE and
replayed; a wall-clock read inside it freezes the trace-time value into
the compiled executable (the bucket-expiry arithmetic then silently uses
a stale `now` forever), and a Python branch on a tracer either throws a
ConcretizationTypeError at runtime or — worse — bakes one branch in.
The kernels take `now` as an argument for exactly this reason
(ops/step.py); this checker keeps it that way.

Flags, in any function reachable from a jit/shard_map entry point via
same-module calls:

  time.time / time.time_ns / time.monotonic / time.perf_counter
  datetime.now / datetime.utcnow / Clock reads (.now(),
  .millisecond_now(), time.time_ns via core.clock)
  float()/int()/bool() casts and `.item()` reads of function parameters
  `if`/`while` tests on bare (non-static) parameters
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.gubguard.core import Checker, Finding, ModuleInfo, dotted_name

_IMPURE_DOTTED = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow",
}
_CLOCK_METHODS = {"now", "millisecond_now", "utcnow"}


def _jit_targets(tree: ast.Module) -> Set[str]:
    """Names of module functions passed to jax.jit / shard_map (call or
    decorator form, directly or through functools.partial)."""
    targets: Set[str] = set()

    def is_jit_callable(fn: ast.AST) -> bool:
        dn = dotted_name(fn)
        if dn is None:
            return False
        last = dn.split(".")[-1]
        return last in ("jit", "shard_map", "_shard_map", "pallas_call")

    def first_name_arg(call: ast.Call) -> Optional[str]:
        for a in call.args:
            dn = dotted_name(a)
            if dn is not None:
                return dn.split(".")[-1]
        return None

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and is_jit_callable(node.func):
            nm = first_name_arg(node)
            if nm:
                targets.add(nm)
        elif isinstance(node, ast.Call) and dotted_name(node.func) in (
            "functools.partial", "partial"
        ):
            if node.args and is_jit_callable(node.args[0]):
                nm = None
                for a in node.args[1:]:
                    dn = dotted_name(a)
                    if dn is not None:
                        nm = dn.split(".")[-1]
                        break
                if nm:
                    targets.add(nm)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                fn = dec.func if isinstance(dec, ast.Call) else dec
                if is_jit_callable(fn):
                    targets.add(node.name)
                elif isinstance(dec, ast.Call) and dotted_name(fn) in (
                    "functools.partial", "partial"
                ):
                    if dec.args and is_jit_callable(dec.args[0]):
                        targets.add(node.name)
    return targets


def _static_argnames(tree: ast.Module) -> Set[str]:
    """Every name listed in any static_argnames/static_argnums kwarg —
    branches on those params are legitimate trace-time Python."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        for kw in node.keywords:
            if kw.arg != "static_argnames":
                continue
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.add(e.value)
    return names


def _time_aliases(tree: ast.Module):
    """(module aliases of time/datetime, names bound by `from time
    import time`-style imports)."""
    mod_alias = {}
    fn_alias = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("time", "datetime"):
                    mod_alias[a.asname or a.name] = a.name
        elif isinstance(node, ast.ImportFrom):
            if node.module in ("time", "datetime"):
                for a in node.names:
                    if a.name in (
                        "time", "time_ns", "monotonic", "perf_counter",
                        "now", "utcnow", "datetime",
                    ):
                        if a.name == "datetime":
                            mod_alias[a.asname or a.name] = "datetime"
                        else:
                            fn_alias.add(a.asname or a.name)
    return mod_alias, fn_alias


class JitPurityChecker(Checker):
    name = "jit-purity"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        tree = mod.tree
        targets = _jit_targets(tree)
        if not targets:
            return ()
        static_names = _static_argnames(tree)
        self._mod_alias, self._fn_alias = _time_aliases(tree)

        defs: Dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)
            elif isinstance(node, ast.Assign):
                # `impl = lambda ...` / `fn = other_fn` aliases
                if (
                    len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Lambda)
                ):
                    defs.setdefault(node.targets[0].id, node.value)

        # BFS the same-module call graph from the jit roots.
        reachable: Set[str] = set()
        frontier = [t for t in targets if t in defs]
        while frontier:
            nm = frontier.pop()
            if nm in reachable:
                continue
            reachable.add(nm)
            for node in ast.walk(defs[nm]):
                if isinstance(node, ast.Call):
                    dn = dotted_name(node.func)
                    if dn and "." not in dn and dn in defs:
                        frontier.append(dn)

        out: List[Finding] = []
        for nm in sorted(reachable):
            out.extend(self._check_fn(mod, nm, defs[nm], static_names))
        return out

    def _check_fn(
        self, mod: ModuleInfo, nm: str, fn: ast.AST, static: Set[str]
    ) -> Iterable[Finding]:
        params: Set[str] = set()
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            a = fn.args
            for arg in (
                list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
            ):
                params.add(arg.arg)
        tracer_params = params - static
        out: List[Finding] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                msg = self._impure_call(node, tracer_params)
                if msg:
                    out.append(Finding(
                        checker=self.name, path=mod.relpath,
                        line=node.lineno,
                        message=f"in jit-reachable '{nm}': {msg}",
                    ))
            elif isinstance(node, (ast.If, ast.While)):
                leak = self._tracer_branch(node.test, tracer_params)
                if leak:
                    out.append(Finding(
                        checker=self.name, path=mod.relpath,
                        line=node.lineno,
                        message=(
                            f"in jit-reachable '{nm}': python branch on "
                            f"parameter '{leak}' — a tracer under jit; "
                            "use jnp.where / lax.cond (or declare it in "
                            "static_argnames)"
                        ),
                    ))
        return out

    def _impure_call(self, node: ast.Call, tracer_params: Set[str]) -> str:
        dn = dotted_name(node.func)
        if dn is not None and "." in dn:
            # Resolve `import time as t` aliases to the real module.
            root, rest = dn.split(".", 1)
            real = self._mod_alias.get(root)
            if real is not None:
                dn = f"{real}.{rest}"
        if dn in _IMPURE_DOTTED:
            return (
                f"wall-clock read '{dn}' freezes into the trace; pass "
                "`now` as an argument (ops/step.py discipline)"
            )
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in self._fn_alias
        ):
            return (
                f"wall-clock read '{node.func.id}()' (imported from "
                "time/datetime) freezes into the trace; pass `now` as "
                "an argument"
            )
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in _CLOCK_METHODS and not node.args:
                base = dotted_name(node.func.value) or ""
                if "clock" in base.lower() or base.split(".")[-1] in (
                    "datetime",
                ):
                    return (
                        f"clock read '{base}.{node.func.attr}()' freezes "
                        "into the trace; pass `now` as an argument"
                    )
            if node.func.attr == "item":
                base = dotted_name(node.func.value)
                if base in tracer_params:
                    return (
                        f"'.item()' on parameter '{base}' concretizes a "
                        "tracer (host sync + trace break)"
                    )
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ("float", "int", "bool")
            and len(node.args) == 1
        ):
            base = dotted_name(node.args[0])
            if base in tracer_params:
                return (
                    f"'{node.func.id}({base})' concretizes a tracer "
                    "parameter"
                )
        return ""

    @staticmethod
    def _tracer_branch(
        test: ast.AST, tracer_params: Set[str]
    ) -> Optional[str]:
        # Only bare `if param:` / `if param <op> const:` forms — richer
        # expressions (shape reads, `is None` checks) are trace-time.
        if isinstance(test, ast.Name) and test.id in tracer_params:
            return test.id
        if isinstance(test, ast.Compare):
            for cmp_op in test.ops:
                if isinstance(cmp_op, (ast.Is, ast.IsNot, ast.In, ast.NotIn)):
                    return None
            sides = [test.left] + list(test.comparators)
            names = [s.id for s in sides if isinstance(s, ast.Name)]
            consts = [s for s in sides if isinstance(s, ast.Constant)]
            if len(sides) == 2 and len(consts) == 1:
                for nm in names:
                    if nm in tracer_params:
                        return nm
        return None
