"""lock-complete: every lock the codebase constructs is accounted for.

The lock-order ranking (lockorder.py) is only as good as its coverage:
a lock nobody registered is a lock the global order says nothing
about, and the lexical inversion checker will happily pass code that
deadlocks through it.  This checker closes the loop — every
`threading.Lock()` / `threading.RLock()` / `asyncio.Lock()` /
`threading.Condition()` CONSTRUCTED under the scanned tree must be

  * mapped to a canonical name by lockorder.CLASS_LOCK_MAP *and*
    ranked in lockorder.RANK, or
  * explicitly waived in WAIVERS with a reason (Conditions — which
    coordinate, not rank; function-local locks that never escape;
    module-level import guards taken alone).

Unaccounted construction is an error; so is a stale waiver that no
longer matches any construction site (a renamed lock must not leave a
dangling hall pass behind).
"""
from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable, List, Optional, Set, Tuple

from tools.gubguard.core import Checker, Finding, ModuleInfo, dotted_name
from tools.gubguard.lockorder import CLASS_LOCK_MAP, RANK

# Constructors that create a mutual-exclusion participant.
_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "asyncio.Lock",
}
# Constructors that create a coordination primitive — never ranked,
# always waived explicitly.
_COND_CTORS = {
    "threading.Condition", "asyncio.Condition",
}

# key -> reason.  Keys: "Class.attr" for instance attributes,
# "<relpath>::<name>" for module-level and function-local locks.
WAIVERS = {
    "PersistenceHost._wt_cond": (
        "writer-thread Condition: coordinates the snapshot writer's "
        "sleep/wake, never guards shared state against the request "
        "path (the data it signals about rides backend._lock)"
    ),
    "RingBackend._cond": (
        "host-job FIFO Condition: wakes the ring worker when a job "
        "lands; the queue itself is only touched under the Condition's "
        "own lock, taken alone"
    ),
    "TierManager._cv": (
        "tier-worker Condition: demote/promote wakeup only; row state "
        "is guarded by coldtier._lock (rank 54), not by this"
    ),
    "gubernator_tpu/runtime/fastpath.py::gate": (
        "function-local Lock handed to one drain closure; never "
        "stored on an object, cannot participate in cross-path nesting"
    ),
    "gubernator_tpu/native/__init__.py::_load_lock": (
        "module-level import guard: serializes the one-time native "
        "library load, taken alone at first use, takes nothing while "
        "held"
    ),
}


def _ctor_kind(node: ast.AST) -> Optional[str]:
    """'lock' / 'cond' when `node` constructs a primitive we track."""
    if not isinstance(node, ast.Call):
        return None
    dn = dotted_name(node.func)
    if dn in _LOCK_CTORS:
        return "lock"
    if dn in _COND_CTORS:
        return "cond"
    return None


class _CtorVisitor(ast.NodeVisitor):
    def __init__(self, mod: ModuleInfo) -> None:
        self.mod = mod
        self.class_stack: List[str] = []
        self.fn_depth = 0
        # (key, line, kind, desc) per construction site
        self.sites: List[Tuple[str, int, str, str]] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.fn_depth += 1
        self.generic_visit(node)
        self.fn_depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def _record(self, target: ast.AST, kind: str, line: int) -> None:
        cls = self.class_stack[-1] if self.class_stack else None
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
            and cls is not None
        ):
            self.sites.append(
                (f"{cls}.{target.attr}", line, kind,
                 f"self.{target.attr} in class {cls}")
            )
        elif isinstance(target, ast.Name):
            scope = "local" if self.fn_depth else "module-level"
            self.sites.append(
                (f"{self.mod.relpath}::{target.id}", line, kind,
                 f"{scope} name '{target.id}'")
            )
        else:
            self.sites.append(
                (f"{self.mod.relpath}::<anonymous>", line, kind,
                 "unrecognized assignment target")
            )

    def visit_Assign(self, node: ast.Assign) -> None:
        kind = _ctor_kind(node.value)
        if kind is not None:
            for t in node.targets:
                self._record(t, kind, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            kind = _ctor_kind(node.value)
            if kind is not None:
                self._record(node.target, kind, node.lineno)
        self.generic_visit(node)


class LockCompleteChecker(Checker):
    name = "lock-complete"

    def __init__(self) -> None:
        self.matched_waivers: Set[str] = set()
        self.saw_any = False

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        v = _CtorVisitor(mod)
        v.visit(mod.tree)
        out: List[Finding] = []
        for key, line, kind, desc in v.sites:
            self.saw_any = True
            if mod.suppressed(line, self.name):
                continue
            if key in WAIVERS:
                self.matched_waivers.add(key)
                continue
            if kind == "cond":
                out.append(Finding(
                    checker=self.name, path=mod.relpath, line=line,
                    message=(
                        f"Condition construction ({desc}) is not in the "
                        "lock-complete waiver list — conditions are "
                        "never ranked, so each needs an explicit waiver "
                        "stating what it coordinates "
                        "(tools/gubguard/lockcomplete.py WAIVERS)"
                    ),
                ))
                continue
            # instance-attribute lock: must resolve through
            # CLASS_LOCK_MAP into a RANKed canonical name.
            if "::" not in key:
                cls, _, attr = key.partition(".")
                canon = CLASS_LOCK_MAP.get((cls, attr))
                if canon is None:
                    out.append(Finding(
                        checker=self.name, path=mod.relpath, line=line,
                        message=(
                            f"lock {desc} is not registered: add "
                            f"('{cls}', '{attr}') to "
                            "lockorder.CLASS_LOCK_MAP and rank the "
                            "canonical name, or waive it with a reason"
                        ),
                    ))
                elif canon not in RANK:
                    out.append(Finding(
                        checker=self.name, path=mod.relpath, line=line,
                        message=(
                            f"lock {desc} maps to '{canon}' which has "
                            "no rank in lockorder.RANK — an unranked "
                            "lock is invisible to the global-order check"
                        ),
                    ))
            else:
                out.append(Finding(
                    checker=self.name, path=mod.relpath, line=line,
                    message=(
                        f"lock construction ({desc}) escapes the "
                        "class-attribute discipline — rank it or waive "
                        f"'{key}' in lockcomplete.WAIVERS with a reason"
                    ),
                ))
        return out

    def finalize(self, root: Path) -> Iterable[Finding]:
        if not self.saw_any:
            return []
        stale = sorted(set(WAIVERS) - self.matched_waivers)
        return [
            Finding(
                checker=self.name,
                path="tools/gubguard/lockcomplete.py", line=1,
                message=(
                    f"stale lock waiver '{key}' matches no construction "
                    "site — remove it (a renamed lock must not keep a "
                    "dangling hall pass)"
                ),
                severity="warning",
            )
            for key in stale
        ]
