"""env-parity: the GUBER_*/GUBTRACE_* env surface must match docs + the
reference.

Three-way diff between

  parsed     -- GUBER_*/GUBTRACE_* string literals in the scanned
                python modules (core/config.py is the canonical parse
                site — gubtrace's knobs route through it too);
  referenced -- env tokens in README.md, docs/ and deploy/ (what we
                promise operators);
  reference  -- the Go reference daemon's env surface (config.go), the
                compatibility target (GUBER_* only; GUBTRACE_* is this
                build's tooling surface).

Rules:
  * referenced-but-not-parsed is an ERROR: a manifest or doc promises a
    knob the daemon silently ignores (the worst failure mode for a rate
    limiter — an operator "sets" a limit control and nothing happens);
  * reference-vars-not-parsed is a WARNING listing the untranslated
    set (the VERDICT parity gap), minus the vars that are structurally
    inapplicable to the TPU rebuild;
  * parsed-but-undocumented (absent from deploy/example.conf) is a
    WARNING: every supported knob must be discoverable.

OTEL_* is an ACKNOWLEDGED external namespace, not drift: it is the
OpenTelemetry SDK's own env spec (runtime/tracing.py reads the subset
it implements; an attached OTel SDK reads more).  Docs may therefore
reference OTEL_ vars this repo never parses — only the
parsed-but-undocumented warning applies to them (an OTEL_ var our code
DOES read must still appear in deploy/example.conf).  The GUBER_*/
GUBTRACE_*/GUBPROOF_*/GUBRANGE_* rules stay strict and unchanged.
"""
from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, List, Set

from tools.gubguard.core import Checker, Finding, ModuleInfo

_VAR_RE = re.compile(
    r"\b(?:GUBER|GUBTRACE|GUBPROOF|GUBRANGE)_[A-Z0-9_]+\b"
)
# The acknowledged external namespace: standard OpenTelemetry env vars
# (see module docstring).  Tracked separately so example.conf coverage
# of the vars we parse is still checked, but a documented-only OTEL_
# var is never flagged as a silent no-op.
_OTEL_RE = re.compile(r"\bOTEL_[A-Z0-9_]+\b")

# The Go reference daemon's env surface (config.go:253-504).  Vars the
# rebuild already parses are checked dynamically; this list exists so
# NEW reference vars that appear in neither code nor docs still get
# reported instead of silently drifting.
REFERENCE_VARS: Set[str] = {
    "GUBER_DEBUG", "GUBER_GRPC_ADDRESS", "GUBER_HTTP_ADDRESS",
    "GUBER_STATUS_HTTP_ADDRESS", "GUBER_ADVERTISE_ADDRESS",
    "GUBER_CACHE_SIZE", "GUBER_DATA_CENTER", "GUBER_METRIC_FLAGS",
    "GUBER_BATCH_TIMEOUT", "GUBER_BATCH_WAIT", "GUBER_BATCH_LIMIT",
    "GUBER_GLOBAL_TIMEOUT", "GUBER_GLOBAL_SYNC_WAIT",
    "GUBER_GLOBAL_BATCH_LIMIT",
    "GUBER_MULTI_REGION_TIMEOUT", "GUBER_MULTI_REGION_SYNC_WAIT",
    "GUBER_MULTI_REGION_BATCH_LIMIT",
    "GUBER_PEER_DISCOVERY_TYPE", "GUBER_PEERS", "GUBER_PEER_PICKER",
    "GUBER_PEER_PICKER_HASH", "GUBER_REPLICATED_HASH_REPLICAS",
    "GUBER_DNS_FQDN", "GUBER_DNS_POLL_INTERVAL", "GUBER_RESOLV_CONF",
    "GUBER_ETCD_KEY_PREFIX", "GUBER_ETCD_ENDPOINTS",
    "GUBER_ETCD_DIAL_TIMEOUT", "GUBER_ETCD_USER", "GUBER_ETCD_PASSWORD",
    "GUBER_ETCD_ADVERTISE_ADDRESS", "GUBER_ETCD_TLS_CA",
    "GUBER_ETCD_TLS_CERT", "GUBER_ETCD_TLS_KEY",
    "GUBER_ETCD_TLS_SKIP_VERIFY",
    "GUBER_K8S_NAMESPACE", "GUBER_K8S_ENDPOINTS_SELECTOR",
    "GUBER_K8S_POD_IP", "GUBER_K8S_POD_PORT",
    "GUBER_K8S_WATCH_MECHANISM",
    "GUBER_TLS_CA", "GUBER_TLS_CA_KEY", "GUBER_TLS_CERT",
    "GUBER_TLS_KEY", "GUBER_TLS_CLIENT_AUTH",
    "GUBER_TLS_CLIENT_AUTH_CA_CERT", "GUBER_TLS_CLIENT_AUTH_CERT_FILE",
    "GUBER_TLS_CLIENT_AUTH_KEY_FILE", "GUBER_TLS_INSECURE_SKIP_VERIFY",
    "GUBER_TLS_MIN_VERSION",
    "GUBER_GRPC_MAX_CONN_AGE_SEC", "GUBER_LOG_LEVEL",
    "GUBER_WORKER_COUNT", "GUBER_INSTANCE_ID",
    "GUBER_MEMBERLIST_ADDRESS", "GUBER_MEMBERLIST_ADVERTISE_ADDRESS",
}

# Reference vars with no analog in this architecture (documented in
# docs/invariants.md): the Go worker-pool and memberlist knobs.
INAPPLICABLE: Set[str] = {
    "GUBER_WORKER_COUNT",            # no Go worker pool; the device IS it
    "GUBER_MEMBERLIST_ADDRESS",      # memberlist -> gossip (GUBER_GOSSIP_*)
    "GUBER_MEMBERLIST_ADVERTISE_ADDRESS",
    "GUBER_INSTANCE_ID",
}

_DOC_GLOBS = ("README.md", "docs/**/*.md", "deploy/**/*")
_EXAMPLE_CONF = "deploy/example.conf"


class EnvParityChecker(Checker):
    name = "env-parity"

    def __init__(self) -> None:
        self.parsed: Set[str] = set()
        self.parsed_otel: Set[str] = set()
        self.saw_config = False

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        if mod.relpath.endswith("core/config.py"):
            self.saw_config = True
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                self.parsed.update(_VAR_RE.findall(node.value))
                self.parsed_otel.update(_OTEL_RE.findall(node.value))
        return ()

    def finalize(self, root: Path) -> Iterable[Finding]:
        if not self.saw_config:
            # Partial scan (single file / subpackage): the parsed set is
            # incomplete, so a doc diff would be all false positives.
            return ()
        referenced: Dict[str, List[str]] = {}
        for pattern in _DOC_GLOBS:
            for p in sorted(root.glob(pattern)):
                if not p.is_file():
                    continue
                try:
                    text = p.read_text(encoding="utf-8", errors="replace")
                except OSError:
                    continue
                rel = p.relative_to(root).as_posix()
                for var in set(_VAR_RE.findall(text)):
                    referenced.setdefault(var, []).append(rel)

        out: List[Finding] = []
        for var in sorted(referenced):
            # `GUBER_GOSSIP_*`-style wildcard prefixes and the bare
            # prefix aren't var names; INAPPLICABLE vars may appear in
            # docs as documented exemptions.
            if var.endswith("_") or var in INAPPLICABLE:
                continue
            if var not in self.parsed:
                where = ", ".join(referenced[var][:3])
                out.append(Finding(
                    checker=self.name, path=where.split(",")[0], line=1,
                    message=(
                        f"'{var}' is documented ({where}) but never "
                        "parsed — an operator setting it gets a silent "
                        "no-op"
                    ),
                ))

        untranslated = sorted(
            REFERENCE_VARS - self.parsed - INAPPLICABLE
        )
        if untranslated:
            out.append(Finding(
                checker=self.name, path="gubernator_tpu/core/config.py",
                line=1, severity="warning",
                message=(
                    f"{len(untranslated)} reference env vars not yet "
                    "translated: " + ", ".join(untranslated)
                ),
            ))

        conf = root / _EXAMPLE_CONF
        if conf.is_file():
            try:
                conf_text = conf.read_text(encoding="utf-8")
            except OSError:
                conf_text = ""
            doc_vars = set(_VAR_RE.findall(conf_text))
            undocumented = sorted(
                v for v in self.parsed - doc_vars if v != "GUBER_"
            )
            if undocumented:
                out.append(Finding(
                    checker=self.name, path=_EXAMPLE_CONF, line=1,
                    severity="warning",
                    message=(
                        "parsed but absent from example.conf: "
                        + ", ".join(undocumented)
                    ),
                ))
            # OTEL_* (acknowledged external namespace): only the vars
            # runtime/tracing.py actually READS must be discoverable in
            # example.conf — documented-only OTEL_ vars belong to the
            # OTel SDK's spec and are never drift.
            otel_doc = set(_OTEL_RE.findall(conf_text))
            otel_missing = sorted(self.parsed_otel - otel_doc)
            if otel_missing:
                out.append(Finding(
                    checker=self.name, path=_EXAMPLE_CONF, line=1,
                    severity="warning",
                    message=(
                        "OTEL_ vars read by the runtime but absent "
                        "from example.conf: " + ", ".join(otel_missing)
                    ),
                ))
        return out
