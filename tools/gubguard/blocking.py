"""async-blocking: no blocking calls inside `async def` bodies.

The daemon serves every RPC on one event loop; a single blocking call
stalls every in-flight request and the batcher windows (the raceguard
runtime plugin measures these stalls — this checker catches them before
they run).  Flags, lexically inside an `async def` (but not inside a
nested sync `def`, which is usually an executor callback):

  time.sleep(...)                  use asyncio.sleep
  open(...) / Path.read_text(...)  use a thread (loop.run_in_executor)
  sync gRPC channels/servers       use grpc.aio
  subprocess.run/call/check_*      use asyncio.create_subprocess_*
  socket.getaddrinfo & friends     use loop.getaddrinfo / loop.run_in_executor
  requests.* / urllib urlopen      use aiohttp
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from tools.gubguard.core import Checker, Finding, ModuleInfo, dotted_name

_BLOCKING_DOTTED = {
    "time.sleep": "use 'await asyncio.sleep(...)'",
    "grpc.insecure_channel": "use 'grpc.aio.insecure_channel'",
    "grpc.secure_channel": "use 'grpc.aio.secure_channel'",
    "grpc.server": "use 'grpc.aio.server'",
    "subprocess.run": "use 'asyncio.create_subprocess_exec'",
    "subprocess.call": "use 'asyncio.create_subprocess_exec'",
    "subprocess.check_call": "use 'asyncio.create_subprocess_exec'",
    "subprocess.check_output": "use 'asyncio.create_subprocess_exec'",
    "socket.getaddrinfo": "use 'loop.getaddrinfo'",
    "socket.gethostbyname": "use 'loop.getaddrinfo'",
    "socket.create_connection": "use 'asyncio.open_connection'",
    "urllib.request.urlopen": "use aiohttp",
    "os.system": "use 'asyncio.create_subprocess_shell'",
}
_BLOCKING_NAMES = {
    "open": "wrap file I/O in 'loop.run_in_executor' (or read at init)",
    "input": "never block the loop on stdin",
}
_BLOCKING_METHODS = {
    "read_text": "pathlib file I/O blocks; run it in an executor",
    "read_bytes": "pathlib file I/O blocks; run it in an executor",
    "write_text": "pathlib file I/O blocks; run it in an executor",
    "write_bytes": "pathlib file I/O blocks; run it in an executor",
}


class _AsyncVisitor(ast.NodeVisitor):
    def __init__(self, checker: "BlockingChecker", mod: ModuleInfo) -> None:
        self.checker = checker
        self.mod = mod
        self.findings: List[Finding] = []
        self._async_depth = 0
        # Names bound by `from time import sleep`-style imports.
        self._time_sleep_aliases = set()
        self._requests_aliases = set()

    # -- scope tracking --------------------------------------------------
    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "time":
            for a in node.names:
                if a.name == "sleep":
                    self._time_sleep_aliases.add(a.asname or a.name)
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.name == "requests":
                self._requests_aliases.add(a.asname or a.name)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._async_depth += 1
        for child in node.body:
            self.visit(child)
        self._async_depth -= 1

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # A sync def nested in an async def runs elsewhere (executor
        # callback, functools helper) — not on the loop.
        saved = self._async_depth
        self._async_depth = 0
        for child in node.body:
            self.visit(child)
        self._async_depth = saved

    def visit_Lambda(self, node: ast.Lambda) -> None:
        saved = self._async_depth
        self._async_depth = 0
        self.generic_visit(node)
        self._async_depth = saved

    # -- the check -------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        if self._async_depth > 0:
            msg = self._classify(node)
            if msg:
                self.findings.append(Finding(
                    checker=self.checker.name, path=self.mod.relpath,
                    line=node.lineno, message=msg,
                ))
        self.generic_visit(node)

    def _classify(self, node: ast.Call) -> Optional[str]:
        fn = node.func
        dn = dotted_name(fn)
        if dn:
            hint = _BLOCKING_DOTTED.get(dn)
            if hint:
                return f"blocking '{dn}' in async def: {hint}"
            root = dn.split(".", 1)[0]
            if root in self._requests_aliases and "." in dn:
                return (
                    f"blocking '{dn}' (sync HTTP) in async def: "
                    "use aiohttp"
                )
        if isinstance(fn, ast.Name):
            if fn.id in self._time_sleep_aliases:
                return (
                    "blocking 'time.sleep' in async def: use "
                    "'await asyncio.sleep(...)'"
                )
            hint = _BLOCKING_NAMES.get(fn.id)
            if hint:
                return f"blocking '{fn.id}(...)' in async def: {hint}"
        if isinstance(fn, ast.Attribute):
            hint = _BLOCKING_METHODS.get(fn.attr)
            if hint:
                return f"blocking '.{fn.attr}(...)' in async def: {hint}"
        return None


class BlockingChecker(Checker):
    name = "async-blocking"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        v = _AsyncVisitor(self, mod)
        v.visit(mod.tree)
        return v.findings
