"""Conformance linting: every state write in the code is a spec edge.

The gubguard/gubtrace discipline applied one layer up: the AST pass
maps every state-variable write site in a protocol module to a declared
transition in its spec, and fails on

  * an UNDECLARED TRANSITION — a write (or container mutation, or
    watched residency call) no spec edge covers;
  * a MISSING GUARD — the write is declared, but none of the matching
    edges finds its guard terms in the site's guard context;
  * a SPEC EDGE WITH NO IMPLEMENTATION SITE — the spec promises a
    transition the code cannot perform.

Matching is deliberately syntactic and local (this is a linter, not a
verifier):

  * a site in function F matches edge E when E.fn == F, or E.fn is a
    function that directly calls F (one level of helper indirection —
    `record_failure` -> `_open` -> `_set_state`);
  * the guard context of a match is every identifier term (Name ids
    and attribute names) appearing in an `if`/`while`/ternary/`assert`
    test or a comprehension filter of F or of E.fn;
  * `from`-state correctness is NOT checked here — it is checked
    dynamically by the explorer (tools/gubproof/explore.py), which
    fires every edge of the abstract model and validates each against
    the spec's (from, to) pairs.

Construction is not a transition: a write in `__init__` (or a
dataclass class-body default) that resolves to the machine's declared
initial state needs no edge; resolving to anything else is an error.

Suppression rides the gubguard pragma: `# gubproof: ok` on the flagged
line or the line above (same grammar as `# gubguard: ok`).
"""
from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.gubguard.core import Finding, load_module
from tools.gubproof.spec import Machine, ProtocolSpec, Transition

CHECKER = "conformance"

_PRAGMA_RE = re.compile(r"#\s*gubproof:\s*ok(?:=(?P<names>[\w,\-]+))?")

# Container methods that mutate a dict-machine's membership.  Anything
# here that is not a declarable op (setitem/delitem/pop/setdefault) can
# never match an edge, so `.clear()`/`.update()` on a state container
# is always an undeclared transition — the right strictness.
_DICT_MUTATORS = ("pop", "setdefault", "update", "clear", "popitem")


@dataclass
class _Site:
    """One state-write site resolved from the AST."""

    fn: str  # enclosing function name ("" = module/class body)
    cls: str  # enclosing class name ("" = module level)
    line: int
    kind: str  # "attr" | "dict" | "call"
    to_state: str = ""  # attr kind: resolved target state
    op: str = ""  # dict kind
    call: str = ""  # calls kind
    desc: str = ""  # human-readable site description


class _Index(ast.NodeVisitor):
    """Function/class index + per-function guard context + call graph."""

    def __init__(self) -> None:
        self.funcs: Dict[str, ast.AST] = {}
        self.fn_of_node: Dict[int, str] = {}
        self.cls_of_node: Dict[int, str] = {}
        self._fn_stack: List[str] = []
        self._cls_stack: List[str] = []
        # fn name -> method/function names it calls directly
        self.calls: Dict[str, Set[str]] = {}
        # fn name -> identifier terms in its branch tests
        self.guard_ctx: Dict[str, Set[str]] = {}

    def _enter(self, node: ast.AST) -> None:
        fn = self._fn_stack[-1] if self._fn_stack else ""
        cls = self._cls_stack[-1] if self._cls_stack else ""
        self.fn_of_node[id(node)] = fn
        self.cls_of_node[id(node)] = cls

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._enter(node)
        self._cls_stack.append(node.name)
        self.generic_visit(node)
        self._cls_stack.pop()

    def _visit_func(self, node) -> None:
        self._enter(node)
        # Nested defs keep the outer name: sites in a closure belong to
        # the enclosing API function for matching purposes.
        name = self._fn_stack[-1] if self._fn_stack else node.name
        if not self._fn_stack:
            self.funcs[node.name] = node
            self.calls.setdefault(node.name, set())
            self.guard_ctx.setdefault(node.name, set())
        self._fn_stack.append(name)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _note_test(self, test: ast.AST) -> None:
        if self._fn_stack:
            self.guard_ctx[self._fn_stack[-1]].update(_terms(test))

    def visit_If(self, node: ast.If) -> None:
        self._enter(node)
        self._note_test(node.test)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._enter(node)
        self._note_test(node.test)
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._enter(node)
        self._note_test(node.test)
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._enter(node)
        self._note_test(node.test)
        self.generic_visit(node)

    def visit_comprehension(self, node: ast.comprehension) -> None:
        for cond in node.ifs:
            self._note_test(cond)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        self._enter(node)
        if self._fn_stack:
            callee = None
            if isinstance(node.func, ast.Attribute):
                callee = node.func.attr
            elif isinstance(node.func, ast.Name):
                callee = node.func.id
            if callee:
                self.calls[self._fn_stack[-1]].add(callee)
        self.generic_visit(node)

    def generic_visit(self, node: ast.AST) -> None:
        if id(node) not in self.fn_of_node:
            self._enter(node)
        super().generic_visit(node)


def _terms(node: ast.AST) -> Set[str]:
    """Every identifier term in an expression: Name ids and attribute
    names (so `self.cfg.max_holders` contributes both `cfg` and
    `max_holders`)."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def _gubproof_pragmas(source: str) -> Dict[int, Set[str]]:
    pragmas: Dict[int, Set[str]] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                continue
            names = m.group("names")
            pragmas[tok.start[0]] = (
                set(n.strip() for n in names.split(",") if n.strip())
                if names else {"*"}
            )
    except tokenize.TokenError:
        pass
    return pragmas


def _suppressed(pragmas: Dict[int, Set[str]], line: int) -> bool:
    for ln in (line, line - 1):
        names = pragmas.get(ln)
        if names and ("*" in names or CHECKER in names):
            return True
    return False


def _resolve_states(
    node: ast.AST, consts: Dict[str, str]
) -> Optional[List[str]]:
    """Resolve a written value to spec state name(s): a bare Name, a
    dotted name (full chain or last segment), or a ternary (both
    branches).  None = unresolvable."""
    if isinstance(node, ast.IfExp):
        a = _resolve_states(node.body, consts)
        b = _resolve_states(node.orelse, consts)
        if a is None or b is None:
            return None
        return a + b
    if isinstance(node, ast.Name):
        st = consts.get(node.id)
        return [st] if st is not None else None
    if isinstance(node, ast.Attribute):
        parts: List[str] = []
        cur: ast.AST = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if isinstance(cur, ast.Name):
            parts.append(cur.id)
            dotted = ".".join(reversed(parts))
            st = consts.get(dotted, consts.get(parts[0]))
            return [st] if st is not None else None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        # A raw string literal: valid only if it IS a state name.
        return [node.value] if node.value in consts.values() else None
    return None


def _recv_attr(node: ast.AST, receivers: Tuple[str, ...], attr: str) -> bool:
    """True when `node` is `<recv>.<attr>` for a bound receiver."""
    return (
        isinstance(node, ast.Attribute)
        and node.attr == attr
        and isinstance(node.value, ast.Name)
        and node.value.id in receivers
    )


def _fn_matches(t: Transition, site_fn: str, idx: _Index) -> bool:
    if t.fn == site_fn:
        return True
    return site_fn in idx.calls.get(t.fn, ())


def _guard_ok(t: Transition, site_fn: str, idx: _Index) -> bool:
    ctx = set(idx.guard_ctx.get(site_fn, ()))
    if t.fn != site_fn:
        ctx |= idx.guard_ctx.get(t.fn, set())
    return all(g in ctx for g in t.guards)


def _collect_attr_sites(
    tree: ast.Module, m: Machine, idx: _Index
) -> Tuple[List[_Site], List[Finding], str]:
    """Attr-machine sites: direct state-attr writes, setter calls, and
    construction sites (returned separately as findings when they set a
    non-initial state).  Third element is the relpath placeholder filled
    by the caller."""
    sites: List[_Site] = []
    bad: List[Finding] = []
    receivers = m.receivers or ("self",)
    for node in ast.walk(tree):
        fn = idx.fn_of_node.get(id(node), "")
        cls = idx.cls_of_node.get(id(node), "")
        # Class-body default (dataclass field): the initial-state rule.
        if (
            isinstance(node, (ast.AnnAssign, ast.Assign))
            and not fn
            and cls == m.owner_class
        ):
            targets = (
                [node.target] if isinstance(node, ast.AnnAssign)
                else node.targets
            )
            for tgt in targets:
                if isinstance(tgt, ast.Name) and tgt.id == m.state_attr:
                    val = getattr(node, "value", None)
                    if val is None:
                        continue
                    states = _resolve_states(val, m.state_consts)
                    if states != [m.initial]:
                        bad.append(_finding(
                            node.lineno,
                            f"{m.owner_class}.{m.state_attr} default "
                            f"must be the declared initial state "
                            f"{m.initial!r} (machine {m.name})",
                        ))
            continue
        if not fn:
            continue
        written: Optional[ast.AST] = None
        line = getattr(node, "lineno", 0)
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                [node.target] if isinstance(node, ast.AnnAssign)
                else node.targets
            )
            if any(_recv_attr(t, receivers, m.state_attr) for t in targets):
                written = getattr(node, "value", None)
        elif isinstance(node, ast.AugAssign):
            if _recv_attr(node.target, receivers, m.state_attr):
                bad.append(_finding(
                    line,
                    f"augmented write to {m.state_attr} is never a "
                    f"declarable transition (machine {m.name})",
                ))
                continue
        elif isinstance(node, ast.Call) and m.setter:
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == m.setter
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in receivers
                and node.args
            ):
                written = node.args[0]
        if written is None:
            continue
        if fn == m.setter:
            continue  # the setter's own mechanics, not a transition
        states = _resolve_states(written, m.state_consts)
        if states is None:
            bad.append(_finding(
                line,
                f"{fn} writes {m.state_attr} with a value that does "
                f"not resolve to a declared state of machine {m.name} "
                "(only named state constants are allowed)",
            ))
            continue
        for st in states:
            if fn == "__init__" and st == m.initial:
                continue  # construction, not a transition
            sites.append(_Site(
                fn=fn, cls=cls, line=line, kind="attr", to_state=st,
                desc=f"{fn} sets {m.state_attr} -> {st!r}",
            ))
    return sites, bad, ""


def _collect_dict_sites(
    tree: ast.Module, m: Machine, idx: _Index
) -> List[_Site]:
    sites: List[_Site] = []
    receivers = m.receivers or ("self",)
    for node in ast.walk(tree):
        fn = idx.fn_of_node.get(id(node), "")
        cls = idx.cls_of_node.get(id(node), "")
        if not fn:
            continue
        line = getattr(node, "lineno", 0)
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) and _recv_attr(
                    tgt.value, receivers, m.state_attr
                ):
                    sites.append(_Site(
                        fn=fn, cls=cls, line=line, kind="dict",
                        op="setitem",
                        desc=f"{fn}: {m.state_attr}[...] = ...",
                    ))
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) and _recv_attr(
                    tgt.value, receivers, m.state_attr
                ):
                    sites.append(_Site(
                        fn=fn, cls=cls, line=line, kind="dict",
                        op="delitem",
                        desc=f"{fn}: del {m.state_attr}[...]",
                    ))
        elif isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _DICT_MUTATORS
                and _recv_attr(f.value, receivers, m.state_attr)
            ):
                sites.append(_Site(
                    fn=fn, cls=cls, line=line, kind="dict", op=f.attr,
                    desc=f"{fn}: {m.state_attr}.{f.attr}(...)",
                ))
    return sites


def _collect_call_sites(
    tree: ast.Module, m: Machine, idx: _Index
) -> List[_Site]:
    sites: List[_Site] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = idx.fn_of_node.get(id(node), "")
        if not fn:
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in m.watched_calls:
            sites.append(_Site(
                fn=fn, cls=idx.cls_of_node.get(id(node), ""),
                line=node.lineno, kind="call", call=f.attr,
                desc=f"{fn} calls .{f.attr}(...)",
            ))
    return sites


def _finding(line: int, message: str, path: str = "",
             severity: str = "error") -> Finding:
    return Finding(
        checker=CHECKER, path=path, line=line, message=message,
        severity=severity,
    )


def _repath(f: Finding, path: str) -> Finding:
    return Finding(
        checker=f.checker, path=path, line=f.line, message=f.message,
        severity=f.severity,
    )


def lint_machine(
    spec: ProtocolSpec, m: Machine, tree: ast.Module, relpath: str,
    pragmas: Dict[int, Set[str]],
) -> List[Finding]:
    idx = _Index()
    idx.visit(tree)
    out: List[Finding] = []
    if m.kind == "attr":
        sites, bad, _ = _collect_attr_sites(tree, m, idx)
        out.extend(_repath(f, relpath) for f in bad)
    elif m.kind == "dict":
        sites = _collect_dict_sites(tree, m, idx)
    else:
        sites = _collect_call_sites(tree, m, idx)

    implemented: Set[str] = set()
    for site in sites:
        if m.kind == "attr":
            cands = [
                t for t in m.transitions
                if t.to == site.to_state and _fn_matches(t, site.fn, idx)
            ]
        elif m.kind == "dict":
            cands = [
                t for t in m.transitions
                if t.op == site.op and _fn_matches(t, site.fn, idx)
            ]
        else:
            cands = [
                t for t in m.transitions
                if t.call == site.call and _fn_matches(t, site.fn, idx)
            ]
        if not cands:
            out.append(_finding(
                site.line,
                f"undeclared transition: {site.desc} matches no edge "
                f"of spec {spec.id!r} machine {m.name!r}",
                path=relpath,
            ))
            continue
        passing = [t for t in cands if _guard_ok(t, site.fn, idx)]
        if not passing:
            missing = sorted({
                g for t in cands for g in t.guards
                if not _guard_ok(t, site.fn, idx) and g not in
                idx.guard_ctx.get(site.fn, set())
                | idx.guard_ctx.get(t.fn, set())
            })
            out.append(_finding(
                site.line,
                f"missing guard: {site.desc} matches edge(s) "
                f"{', '.join(t.id for t in cands)} of spec "
                f"{spec.id!r} machine {m.name!r}, but guard term(s) "
                f"{missing} appear in no branch test of the site",
                path=relpath,
            ))
            continue
        implemented.update(t.id for t in passing)

    for t in m.transitions:
        if t.id not in implemented:
            out.append(_finding(
                1,
                f"spec edge {t.id!r} "
                f"({'|'.join(t.frm)} -> {t.to}, fn {t.fn}) of machine "
                f"{m.name!r} has no implementation site in {relpath}",
                path=spec_relpath(spec),
            ))
    return [f for f in out if not _suppressed(pragmas, f.line)
            or f.path != relpath]


def spec_relpath(spec: ProtocolSpec) -> str:
    p = spec.path.as_posix()
    i = p.rfind("tools/gubproof/")
    return p[i:] if i >= 0 else p


def lint_spec(spec: ProtocolSpec, root: Path) -> List[Finding]:
    """Lint one protocol spec against its implementation module."""
    mod_path = root / spec.module
    if not mod_path.is_file():
        return [_finding(
            1,
            f"implementation module {spec.module} not found",
            path=spec_relpath(spec),
        )]
    mod = load_module(mod_path, root)
    if mod is None:
        return [_finding(
            1,
            f"implementation module {spec.module} failed to parse",
            path=spec_relpath(spec),
        )]
    pragmas = _gubproof_pragmas(mod.source)
    out: List[Finding] = []
    # Cross-link: the module must point readers at its spec.
    link = f"tools/gubproof/specs/{spec.path.name}"
    if link not in mod.source:
        out.append(_finding(
            1,
            f"module does not cross-link its protocol spec "
            f"({link})",
            path=mod.relpath, severity="warning",
        ))
    for m in spec.machines:
        out.extend(lint_machine(spec, m, mod.tree, mod.relpath, pragmas))
    return out
