"""Spec model: the machine-readable protocol description format.

A spec is one JSON document per protocol plane
(tools/gubproof/specs/<id>.json) declaring

  * one or more state MACHINES — states, initial/terminal sets, and
    guarded transitions, each transition naming the implementation
    function(s) allowed to perform it;
  * the plane's over-admission BOUND — the `admitted <= limit x
    (1 + plane-factor)` instance this plane proves, with the config
    knob that sets the factor;
  * LIVENESS obligations — the "eventually" facts the explorer checks
    by reverse reachability over the closed small-scope state graph.

Machine kinds (what a "state write" means in the implementation):

  attr   an attribute carrying the state (`self.state`, `ob.phase`),
         written directly or through a declared setter; transition
         sites are those writes, resolved through `state_consts`;
  dict   a container whose membership IS the state (lease holders);
         transition sites are setitem/delitem/pop/setdefault on it;
  calls  residency planes with no state variable (the tier): the
         transitions are calls to declared mover functions
         (`cold.put_rows`, `cold.pop_rows`), matched by dotted suffix.

The format is deliberately declarative JSON, not Python: specs are
diffable artifacts a reviewer can read next to docs/*.md prose, and
the linter/explorer are the only interpreters.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple


class SpecError(ValueError):
    """A malformed spec document (fail loudly at load, never at lint)."""


@dataclass(frozen=True)
class Transition:
    id: str
    frm: Tuple[str, ...]  # source states ("*" = any)
    to: str
    fn: str  # implementation function performing the write
    event: str = ""
    guards: Tuple[str, ...] = ()  # identifier terms that must guard fn
    op: str = ""  # dict machines: setitem|delitem|pop|setdefault
    call: str = ""  # calls machines: dotted callee suffix


@dataclass
class Machine:
    name: str
    kind: str  # "attr" | "dict" | "calls"
    owner_class: str
    states: Tuple[str, ...]
    initial: str
    terminal: Tuple[str, ...]
    transitions: List[Transition]
    state_attr: str = ""  # attr/dict kinds: the attribute/container
    setter: str = ""  # attr kind: a transition helper method
    receivers: Tuple[str, ...] = ()  # attr kind: receiver vars to bind
    state_consts: Dict[str, str] = field(default_factory=dict)
    watched_calls: Tuple[str, ...] = ()  # calls kind: site universe

    def transition_pairs(self) -> set:
        """(from, to) pairs the machine declares — the explorer's
        conformance oracle."""
        out = set()
        for t in self.transitions:
            srcs = self.states if t.frm == ("*",) else t.frm
            for s in srcs:
                out.add((s, t.to))
        return out


@dataclass
class Bound:
    formula: str  # e.g. "limit x (1 + max_holders x fraction)"
    factor: str  # prose: what the plane-factor is
    config: str  # the knob(s) that set it


@dataclass
class Liveness:
    id: str
    text: str


@dataclass
class ProtocolSpec:
    id: str
    title: str
    module: str  # repo-relative implementation module
    doc: str  # the prose proof this spec mechanizes
    bound: Bound
    liveness: List[Liveness]
    machines: List[Machine]
    path: Path  # where the spec was loaded from

    def machine(self, name: str) -> Machine:
        for m in self.machines:
            if m.name == name:
                return m
        raise KeyError(name)


def _req(d: dict, key: str, where: str) -> object:
    if key not in d:
        raise SpecError(f"{where}: missing required field {key!r}")
    return d[key]


def _load_machine(d: dict, where: str) -> Machine:
    name = _req(d, "name", where)
    where = f"{where}.{name}"
    kind = _req(d, "kind", where)
    if kind not in ("attr", "dict", "calls"):
        raise SpecError(f"{where}: unknown machine kind {kind!r}")
    states = tuple(_req(d, "states", where))
    initial = _req(d, "initial", where)
    terminal = tuple(d.get("terminal", ()))
    if initial not in states:
        raise SpecError(f"{where}: initial state {initial!r} not in states")
    for t in terminal:
        if t not in states:
            raise SpecError(f"{where}: terminal state {t!r} not in states")
    transitions: List[Transition] = []
    seen_ids = set()
    for td in _req(d, "transitions", where):
        tid = _req(td, "id", where)
        if tid in seen_ids:
            raise SpecError(f"{where}: duplicate transition id {tid!r}")
        seen_ids.add(tid)
        frm = tuple(td.get("from", ("*",)))
        to = _req(td, "to", f"{where}.{tid}")
        for s in frm:
            if s != "*" and s not in states:
                raise SpecError(
                    f"{where}.{tid}: source state {s!r} not in states"
                )
        if to not in states:
            raise SpecError(
                f"{where}.{tid}: target state {to!r} not in states"
            )
        transitions.append(Transition(
            id=tid, frm=frm, to=to,
            fn=_req(td, "fn", f"{where}.{tid}"),
            event=td.get("event", ""),
            guards=tuple(td.get("guards", ())),
            op=td.get("op", ""),
            call=td.get("call", ""),
        ))
    m = Machine(
        name=name, kind=kind,
        owner_class=d.get("owner_class", ""),
        states=states, initial=initial, terminal=terminal,
        transitions=transitions,
        state_attr=d.get("state_attr", ""),
        setter=d.get("setter", ""),
        receivers=tuple(d.get("receivers", ())),
        state_consts=dict(d.get("state_consts", {})),
        watched_calls=tuple(d.get("watched_calls", ())),
    )
    if kind in ("attr", "dict") and not m.state_attr:
        raise SpecError(f"{where}: {kind} machine needs state_attr")
    if kind == "attr":
        for const, st in m.state_consts.items():
            if st not in states:
                raise SpecError(
                    f"{where}: state_consts[{const!r}] -> unknown "
                    f"state {st!r}"
                )
        for t in transitions:
            if t.op or t.call:
                raise SpecError(
                    f"{where}.{t.id}: attr transitions take no op/call"
                )
    if kind == "dict":
        for t in transitions:
            if t.op not in ("setitem", "delitem", "pop", "setdefault"):
                raise SpecError(
                    f"{where}.{t.id}: dict transition needs op in "
                    "setitem|delitem|pop|setdefault"
                )
    if kind == "calls":
        if not m.watched_calls:
            raise SpecError(f"{where}: calls machine needs watched_calls")
        for t in transitions:
            if t.call not in m.watched_calls:
                raise SpecError(
                    f"{where}.{t.id}: call {t.call!r} not in "
                    "watched_calls"
                )
    return m


def load_spec(path: Path) -> ProtocolSpec:
    """Load and validate one spec document."""
    try:
        d = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError) as e:
        raise SpecError(f"{path}: unreadable spec: {e}") from e
    where = path.name
    sid = _req(d, "id", where)
    bd = _req(d, "bound", where)
    bound = Bound(
        formula=_req(bd, "formula", f"{where}.bound"),
        factor=bd.get("factor", ""),
        config=bd.get("config", ""),
    )
    liveness = [
        Liveness(id=_req(ld, "id", f"{where}.liveness"),
                 text=_req(ld, "text", f"{where}.liveness"))
        for ld in d.get("liveness", ())
    ]
    machines = [
        _load_machine(md, where) for md in _req(d, "machines", where)
    ]
    if not machines:
        raise SpecError(f"{where}: a spec needs at least one machine")
    return ProtocolSpec(
        id=sid,
        title=_req(d, "title", where),
        module=_req(d, "module", where),
        doc=d.get("doc", ""),
        bound=bound,
        liveness=liveness,
        machines=machines,
        path=path,
    )
