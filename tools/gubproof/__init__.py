"""gubproof: machine-checked protocol specs for the admission planes.

The third static-analysis plane, one layer above its siblings:

  gubguard   AST invariants over the host source (lock order, host-sync
             containment, env parity, ...)
  gubtrace   jaxpr invariants over what XLA actually compiles
  gubproof   PROTOCOL invariants over the distributed state machines —
             the five interlocking planes that all claim a variant of
             one bound, admitted <= limit x (1 + plane-factor)

Three parts (docs/gubproof.md):

  specs        declarative state-machine specs (states, guarded
               transitions, the per-plane over-admission bound,
               liveness obligations) in tools/gubproof/specs/*.json,
               cross-linked from each protocol module;
  conformance  an AST pass mapping every state-variable write site in
               the real modules to a declared spec edge — an undeclared
               transition, a missing guard, or a spec edge with no
               implementation site is an error;
  explore      an exhaustive explicit-state BFS over small-scope
               abstract oracles of each plane (and the reshard+lease
               composition), checking the admission bound EXACTLY
               (reachable and never exceeded), conservation, and
               liveness; a counterexample is emitted as a seeded
               GUBER_CHAOS_PLAN so testing/chaos.py replays it against
               the real daemon.

Run as:

    python -m tools.gubproof                  # specs + lint + explore
    python -m tools.gubproof --select lint
    python -m tools.gubproof --depth 64 --json
"""
from __future__ import annotations

from pathlib import Path
from typing import List, Optional, Sequence

from tools.gubguard.core import Finding

ALL_PHASES = ("specs", "lint", "explore")

SPEC_DIR = Path(__file__).parent / "specs"


def load_all_specs(spec_dir: Optional[Path] = None) -> list:
    """Every protocol spec in the spec directory, validated."""
    from tools.gubproof.spec import load_spec

    d = spec_dir or SPEC_DIR
    return [load_spec(p) for p in sorted(d.glob("*.json"))]


def run(
    select: Optional[Sequence[str]] = None,
    root: Optional[Path] = None,
    depth: Optional[int] = None,
    dump_dir: Optional[Path] = None,
) -> List[Finding]:
    """Run the selected phases; returns sorted findings (exploration
    results ride back as findings too — a bound that is not tight, a
    violated invariant, or an unmet liveness obligation is an error)."""
    from tools.gubproof.conformance import lint_spec
    from tools.gubproof.explore import explore_all_findings
    from tools.gubproof.spec import SpecError, load_spec

    phases = list(select) if select else list(ALL_PHASES)
    unknown = [p for p in phases if p not in ALL_PHASES]
    if unknown:
        raise ValueError(f"unknown gubproof phases: {unknown}")
    root = root or Path.cwd()
    findings: List[Finding] = []

    specs = []
    for p in sorted(SPEC_DIR.glob("*.json")):
        try:
            specs.append(load_spec(p))
        except SpecError as e:
            findings.append(Finding(
                checker="specs",
                path=p.relative_to(root).as_posix()
                if p.is_relative_to(root) else p.as_posix(),
                line=1, message=str(e),
            ))
    if "lint" in phases:
        for spec in specs:
            findings.extend(lint_spec(spec, root))
    if "explore" in phases:
        findings.extend(
            explore_all_findings(specs, depth=depth, dump_dir=dump_dir)
        )
    findings.sort(key=lambda f: (f.path, f.line, f.checker))
    return findings
