"""CLI: python -m tools.gubproof [--select specs,lint,explore] [--strict]."""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Optional, Sequence

from tools.gubproof import ALL_PHASES, run


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="gubproof",
        description=(
            "Protocol specs, conformance linting, and small-scope model "
            "checking of the over-admission algebra (see docs/gubproof.md)."
        ),
    )
    ap.add_argument(
        "--select", metavar="PHASES",
        help="comma-separated phase subset of: " + ", ".join(ALL_PHASES),
    )
    ap.add_argument(
        "--depth", type=int, default=None, metavar="N",
        help=(
            "BFS depth cap for the explorer; the pinned scopes close "
            "unaided, so an insufficient cap is itself an error "
            "(default: unbounded)"
        ),
    )
    ap.add_argument(
        "--dump-dir", default=None, metavar="DIR",
        help=(
            "write counterexample chaos plans (GUBER_CHAOS_PLAN JSON) "
            "here; honors GUBPROOF_DUMP_DIR (default: gubproof-dumps, "
            "only written on violation)"
        ),
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="treat warnings as errors",
    )
    ap.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings as a JSON array",
    )
    ap.add_argument(
        "--root", default=".",
        help="repo root the linted modules resolve against (default: cwd)",
    )
    args = ap.parse_args(argv)

    select = (
        [s.strip() for s in args.select.split(",") if s.strip()]
        if args.select else None
    )
    if args.dump_dir is not None:
        dump_dir = Path(args.dump_dir)
    else:
        from gubernator_tpu.core.config import gubproof_dump_dir_from_env

        dump_dir = Path(gubproof_dump_dir_from_env())
    depth = args.depth
    if depth is None:
        from gubernator_tpu.core.config import gubproof_depth_from_env

        depth = gubproof_depth_from_env()
    try:
        findings = run(
            select=select, root=Path(args.root),
            depth=depth, dump_dir=dump_dir,
        )
    except ValueError as e:
        print(f"gubproof: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
    errors = [
        f for f in findings
        if f.severity == "error" or (args.strict and f.severity == "warning")
    ]
    warnings = [f for f in findings if f.severity == "warning"]
    if not args.as_json:
        print(
            f"gubproof: {len(errors)} error(s), "
            f"{len(warnings)} warning(s)"
        )
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
