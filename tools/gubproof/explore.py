"""Exhaustive explicit-state exploration of the admission-plane models.

Plain breadth-first enumeration with state interning — the scopes in
tools/gubproof/models.py are pinned small enough (tens to a few
thousand states per plane, low-hundreds-of-thousands for the
composition) that the FULL reachable set closes in well under a
second, so there is no frontier sampling, no partial-order reduction,
and no hashing tricks to mistrust.

What closure buys, per model:

  safety       every reachable state satisfies the model invariant
               (the plane's documented over-admission bound plus a
               conservation check that catches inflation bugs the
               bound alone would miss);
  exactness    each documented maximum is REACHED, not just respected
               — `expect_max` must equal the explored maximum exactly,
               so a silently-loosened bound in the docs fails the same
               as an exceeded one;
  spec x-val   every fired edge must exist in the spec with matching
               (from, to) projections, and for `covered` machines
               every spec edge must fire somewhere and no projection
               may change without an edge (the dynamic complement of
               the conformance linter, which is from-state-blind);
  liveness     every state where an obligation applies can still reach
               a goal state (backward reachability over the closed
               graph — sound and complete at this scope).

A violated invariant yields a counterexample trace (the action-label
path from the initial state), which chaosplan.py lowers to a seeded
GUBER_CHAOS_PLAN for replay against the real daemon.
"""
from __future__ import annotations

import json
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.gubguard.core import Finding
from tools.gubproof.models import Model, build_models
from tools.gubproof.spec import ProtocolSpec, Transition

CHECKER = "explore"


@dataclass
class Violation:
    kind: str  # "invariant" | "edge" | "silent" | "liveness"
    message: str
    trace: Tuple[str, ...]
    state: tuple


@dataclass
class ExploreResult:
    model: str
    states: int = 0
    closed: bool = True
    closure_note: str = ""
    max_counters: Dict[str, int] = field(default_factory=dict)
    fired: Set[Tuple[str, str, str]] = field(default_factory=set)
    violations: List[Violation] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.closed and not self.violations


def _lookup(model: Model, sid: str, machine: str, eid: str) -> Optional[Transition]:
    spec = model.specs.get(sid)
    if spec is None:
        return None
    try:
        m = spec.machine(machine)
    except KeyError:
        return None
    for t in m.transitions:
        if t.id == eid:
            return t
    return None


def explore_model(model: Model, depth: Optional[int] = None) -> ExploreResult:
    """Close the model's reachable state set and check everything."""
    res = ExploreResult(model=model.name)
    init = model.initial()
    index: Dict[tuple, int] = {init: 0}
    states: List[tuple] = [init]
    parents: List[Optional[Tuple[int, str]]] = [None]
    succ_idx: List[List[int]] = []  # forward adjacency, filled per expansion
    bad: Set[int] = set()  # violating states: reported, never expanded

    def trace_to(i: int) -> Tuple[str, ...]:
        labels: List[str] = []
        while parents[i] is not None:
            p, label = parents[i]  # type: ignore[misc]
            labels.append(label)
            i = p
        return tuple(reversed(labels))

    def note_counters(s: tuple) -> None:
        for k, v in model.counters(s).items():
            if v > res.max_counters.get(k, 0):
                res.max_counters[k] = v

    msg = model.invariant(init)
    if msg is not None:
        bad.add(0)
        res.violations.append(Violation("invariant", msg, (), init))
    else:
        note_counters(init)

    frontier = deque([0]) if 0 not in bad else deque()
    level = 0
    while frontier:
        if depth is not None and level >= depth:
            res.closed = False
            res.closure_note = (
                f"depth cap {depth} reached with {len(frontier)} "
                f"states unexpanded — exploration did not close"
            )
            break
        level += 1
        for _ in range(len(frontier)):
            i = frontier.popleft()
            s = states[i]
            while len(succ_idx) <= i:
                succ_idx.append([])
            pb = model.proj(s)
            for label, edges, ns in model.successors(s):
                j = index.get(ns)
                fresh = j is None
                if fresh:
                    j = len(states)
                    index[ns] = j
                    states.append(ns)
                    parents.append((i, label))
                succ_idx[i].append(j)  # type: ignore[arg-type]

                # -- spec cross-validation (every firing, fresh or not)
                pa = model.proj(ns)
                moved: Set[Tuple[str, str, Optional[str]]] = set()
                for sid, mname, eid, ent in edges:
                    res.fired.add((sid, mname, eid))
                    t = _lookup(model, sid, mname, eid)
                    if t is None:
                        res.violations.append(Violation(
                            "edge",
                            f"action '{label}' fired unknown spec edge "
                            f"{sid}.{mname}.{eid}",
                            trace_to(j), ns,
                        ))
                        continue
                    moved.add((sid, mname, ent))
                    before = pb.get((sid, mname, ent))
                    after = pa.get((sid, mname, ent))
                    if before is not None and before not in t.frm:
                        res.violations.append(Violation(
                            "edge",
                            f"action '{label}' fired {sid}.{mname}.{eid} "
                            f"from state '{before}' but the spec declares "
                            f"from {list(t.frm)}",
                            trace_to(j), ns,
                        ))
                    if after is not None and after != t.to:
                        res.violations.append(Violation(
                            "edge",
                            f"action '{label}' fired {sid}.{mname}.{eid} "
                            f"landing in '{after}' but the spec declares "
                            f"to '{t.to}'",
                            trace_to(j), ns,
                        ))
                for key in set(pb) | set(pa):
                    sid, mname, _ent = key
                    if (sid, mname) not in model.covered or key in moved:
                        continue
                    b, a = pb.get(key), pa.get(key)
                    if b is not None and a is not None and b != a:
                        res.violations.append(Violation(
                            "silent",
                            f"action '{label}' moved {sid}.{mname} "
                            f"'{b}' -> '{a}' without firing a spec edge",
                            trace_to(j), ns,
                        ))

                if fresh:
                    msg = model.invariant(ns)
                    if msg is not None:
                        bad.add(j)  # terminal: report once, don't expand
                        res.violations.append(
                            Violation("invariant", msg, trace_to(j), ns)
                        )
                    else:
                        note_counters(ns)
                        frontier.append(j)  # type: ignore[arg-type]
            if len(states) > model.state_cap:
                res.closed = False
                res.closure_note = (
                    f"state cap {model.state_cap} exceeded — the scope "
                    "is no longer small; shrink the model"
                )
                frontier.clear()
                break

    res.states = len(states)
    if not res.closed:
        return res

    # -- exactness: documented maxima reproduced, not just respected ----
    for name, want in model.expect_max.items():
        got = res.max_counters.get(name, 0)
        if got != want:
            res.violations.append(Violation(
                "invariant",
                f"documented bound not reproduced exactly: max "
                f"{name} == {got} explored, spec documents {want}"
                + (" (bound looser than reality)" if got < want else
                   " (bound EXCEEDED)"),
                (), states[0],
            ))

    # -- edge coverage for covered machines ------------------------------
    for sid, mname in model.covered:
        t_ids = {
            t.id for t in model.specs[sid].machine(mname).transitions
        }
        missed = sorted(
            t_ids - {e for s2, m2, e in res.fired if (s2, m2) == (sid, mname)}
        )
        for eid in missed:
            res.violations.append(Violation(
                "edge",
                f"spec edge {sid}.{mname}.{eid} never fired in the "
                f"closed exploration ({res.states} states) — dead spec "
                "edge or model gap",
                (), states[0],
            ))

    # -- liveness: applies-states must reach a goal ----------------------
    rev: List[List[int]] = [[] for _ in states]
    for i, outs in enumerate(succ_idx):
        for j in outs:
            rev[j].append(i)
    live_idx = [i for i in range(len(states)) if i not in bad]
    for oid, applies, goal in model.liveness():
        reach = {i for i in live_idx if goal(states[i])}
        q = deque(reach)
        while q:
            j = q.popleft()
            for i in rev[j]:
                if i not in reach and i not in bad:
                    reach.add(i)
                    q.append(i)
        stuck = [i for i in live_idx if applies(states[i]) and i not in reach]
        if stuck:
            w = min(stuck)  # earliest-interned == a shortest witness
            res.violations.append(Violation(
                "liveness",
                f"obligation '{oid}' unmet: {len(stuck)} reachable "
                f"state(s) where it applies can never reach a goal "
                f"state; witness at depth {len(trace_to(w))}",
                trace_to(w), states[w],
            ))
    return res


def _anchor(model: Model, root: Path) -> str:
    from tools.gubproof.conformance import spec_relpath

    spec = model.specs.get(model.name)
    if spec is not None:
        return spec_relpath(spec)
    return "tools/gubproof/models.py"


def explore_all_findings(
    specs: Sequence[ProtocolSpec],
    depth: Optional[int] = None,
    dump_dir: Optional[Path] = None,
) -> List[Finding]:
    """Explore every model buildable from the loaded specs; violations
    come back as findings, and each counterexample trace is dumped as a
    seeded chaos plan under `dump_dir` for testing/chaos.py replay."""
    from tools.gubproof.chaosplan import plan_from_trace

    findings: List[Finding] = []
    root = Path.cwd()
    for model in build_models(specs):
        res = explore_model(model, depth=depth)
        path = _anchor(model, root)
        if not res.closed:
            findings.append(Finding(
                checker=CHECKER, path=path, line=1,
                message=f"[{model.name}] {res.closure_note}",
            ))
        for k, v in enumerate(res.violations):
            note = ""
            if dump_dir is not None and v.trace:
                dump_dir.mkdir(parents=True, exist_ok=True)
                plan = plan_from_trace(
                    model.name, list(v.trace), v.message, seed=k
                )
                out = dump_dir / f"{model.name}-{k}.chaosplan.json"
                out.write_text(json.dumps(plan, indent=2) + "\n")
                note = f" (chaos plan: {out})"
            findings.append(Finding(
                checker=CHECKER, path=path, line=1,
                message=(
                    f"[{model.name}] {v.kind}: {v.message}"
                    + (f"; trace: {' -> '.join(v.trace)}" if v.trace else "")
                    + note
                ),
            ))
    return findings
