"""Lower an explorer counterexample to a seeded GUBER_CHAOS_PLAN.

A model-checker trace is a sequence of abstract action labels.  The
fault actions among them map onto concrete chaos rules — the same Rule
schema testing/chaos.py loads from GUBER_CHAOS_PLAN — so a violated
bound is not just a report: it ships as a plan the integration harness
replays against the real daemon (`probability=1.0`, bounded
`max_count`, fixed `seed` — deterministic by construction).

Non-fault labels (serve:*, grant:*, tick:*) need no rule: they are the
workload the harness drives anyway.  The model name, the violated
invariant, and the full trace ride along as extra keys —
ChaosPlan.from_dict ignores unknown keys, so the plan stays
self-describing without breaking the loader.
"""
from __future__ import annotations

from typing import Dict, List, Optional

# label (or its prefix before ':') -> chaos Rule dict.  Methods are
# fnmatch globs over the fully-qualified gRPC method name.
_FAULT_RULES: Dict[str, Dict[str, object]] = {
    "fault:prepare_fail": {
        "op": "error", "where": "server", "phase": "before",
        "method": "*Handoff*", "probability": 1.0,
        "status": "UNAVAILABLE", "max_count": 3,
        "message": "gubproof: Handoff(PREPARE) refused",
    },
    "fault:transfer_fail": {
        "op": "error", "where": "server", "phase": "before",
        "method": "*Handoff*", "probability": 1.0,
        "status": "UNAVAILABLE", "max_count": 3,
        "message": "gubproof: Handoff(TRANSFER) refused",
    },
    "fault:cutover_fail": {
        "op": "error", "where": "server", "phase": "before",
        "method": "*Handoff*", "probability": 1.0,
        "status": "UNAVAILABLE", "max_count": 3,
        "message": "gubproof: Handoff(CUTOVER) refused",
    },
    "fault:chunk_lost": {
        "op": "error", "where": "server", "phase": "before",
        "method": "*Migrate*", "probability": 1.0,
        "status": "UNAVAILABLE", "max_count": 1,
        "message": "gubproof: migrate chunk dropped on the wire",
    },
    # The replay-guard counterexample: the handler RAN (rows injected)
    # and then the RPC failed — the sender retries and the chunk is
    # delivered twice.  phase="after" is exactly that window.
    "fault:dup_migrate": {
        "op": "error", "where": "client", "phase": "after",
        "method": "*Migrate*", "probability": 1.0,
        "status": "UNAVAILABLE", "max_count": 1,
        "message": "gubproof: migrate ack dropped after delivery",
    },
    "watchdog:self_cutover": {
        "op": "drop", "where": "client", "phase": "before",
        "method": "*Handoff*", "probability": 1.0, "max_count": 2,
        "message": "gubproof: sender silenced until watchdog fires",
    },
    # A region partition: every WAN arc toward the home region refuses
    # at connect (provably unsent — the carve keeps serving and burns
    # re-queue; the broken cutover-reset variant's counterexample rides
    # the same fault, the widening happens at heal).
    "fault:partition": {
        "op": "error", "where": "client", "phase": "before",
        "method": "*GetPeerRateLimits*", "probability": 1.0,
        "status": "UNAVAILABLE", "max_count": 8,
        "message": "gubproof: region WAN lane severed (partition)",
    },
    # breaker probe failures: the peer path the breaker wraps.
    "fail": {
        "op": "error", "where": "client", "phase": "before",
        "method": "*GetPeerRateLimits*", "probability": 1.0,
        "status": "UNAVAILABLE", "max_count": 4,
        "message": "gubproof: peer batch refused (breaker trip/probe)",
    },
    "sweep:expire": {
        "op": "delay", "where": "client", "phase": "before",
        "method": "*Reconcile*", "probability": 1.0,
        "delay_s": 0.2, "max_count": 4,
        "message": "gubproof: holder partitioned past its lease TTL",
    },
}


def _rule_for(label: str) -> Optional[Dict[str, object]]:
    if label in _FAULT_RULES:
        return dict(_FAULT_RULES[label])
    head = label.split(":", 1)[0]
    if head in _FAULT_RULES:
        return dict(_FAULT_RULES[head])
    # entity-suffixed labels: "sweep:expire:c1" -> "sweep:expire"
    parts = label.rsplit(":", 1)
    if len(parts) == 2 and parts[0] in _FAULT_RULES:
        return dict(_FAULT_RULES[parts[0]])
    return None


def plan_from_trace(
    model_name: str,
    labels: List[str],
    violation: str,
    seed: int = 0,
) -> Dict[str, object]:
    """Build a ChaosPlan-compatible dict from a counterexample trace.
    Deduplicates rules (same fault fired twice needs one rule — the
    max_count already covers repetition) and preserves trace order."""
    rules: List[Dict[str, object]] = []
    seen = set()
    for label in labels:
        rule = _rule_for(label)
        if rule is None:
            continue
        key = (rule["op"], rule["where"], rule["phase"], rule["method"])
        if key in seen:
            continue
        seen.add(key)
        rules.append(rule)
    return {
        "seed": seed,
        "rules": rules,
        # Extra keys: ChaosPlan.from_dict ignores them, humans don't.
        "model": model_name,
        "violation": violation,
        "trace": list(labels),
    }
