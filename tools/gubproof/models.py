"""Small-scope abstract models of the admission planes.

Each model is a finite oracle of one protocol plane (or a composition)
at the pinned small scope — 2 peers, 1 key, 1-2 holders, limits small
enough that the explorer closes the full reachable set — mirroring the
pymodel discipline (core/pymodel.py): pure-python semantics the real
implementation is checked against, here by exhaustive BFS instead of
sampled replay.

Every successor is tagged with the spec edge(s) it fires, so the
explorer cross-validates model against spec BOTH ways: a fired edge
must exist with matching (from, to) states, and every edge of a
model's covered machines must fire somewhere in the closed state
graph (the dynamic complement of the conformance linter's static
`from`-blindness).

The documented over-admission algebra, reproduced EXACTLY (the
explorer fails if a maximum is exceeded OR never reached):

  breaker        probes admitted per open episode  == half_open_probes (1)
  lease          admitted <= L(1 + H*f)            == 6   (L=4, H=2, f=1/4)
  reshard        admitted <= L(1 + f_h)            == 5   (rows delivered)
                 admitted <= 2L + f_h*L            == 9   (rows lost -> fresh)
  tier           admitted <= L(1 + cycles)         == 12  (L=4, 2 cycles)
  reshard+lease  admitted <= L(1 + H*f + f_h)      == 7   (delivered)
                 ... + L on loss                   == 11  (lost -> fresh)
  region         admitted <= L(1 + (R-1)*f_R)      == 5   (L=4, R=2, f_R=1/4)
  region+reshard admitted <= L(1 + f_h) + f_R*L    == 6   (delivered)
                 ... + L on loss                   == 10  (lost -> fresh)

Faithfulness notes (scope limits, docs/gubproof.md):
  * models are single-window — Gregorian/window-reset behavior and
    cross-generation carve accounting (burn -> expire -> slot-drop ->
    regrant inside one window) are out of scope;
  * a violating state is terminal: the explorer reports it and does
    not expand it further;
  * `ReshardModel(replay_guard=False)` deliberately removes the
    `seen_fps` replay guard — the resulting counterexample (a
    re-delivered Migrate chunk re-inflating a row) is the seeded
    chaos-plan round-trip fixture in tests/test_gubproof.py;
  * `RegionModel(cutover_reset=True)` deliberately restores the carve
    slot's allowance at region cutover — the counterexample (partition
    -> burn the carve -> heal -> burn a fresh carve in the same
    window) is the second seeded chaos-plan fixture there.
"""
from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from tools.gubproof.spec import ProtocolSpec

# An edge reference: (spec_id, machine_name, edge_id, entity).
EdgeRef = Tuple[str, str, str, Optional[str]]
# One successor: (action label, fired edges, next state, admitted delta)
Succ = Tuple[str, Tuple[EdgeRef, ...], tuple]


class Model:
    """Base: a finite transition system tagged with spec edges."""

    name: str = "model"
    # (spec_id, machine_name) pairs whose every edge must fire.
    covered: Tuple[Tuple[str, str], ...] = ()
    # counter name -> exact maximum the closed exploration must reach.
    expect_max: Dict[str, int] = {}
    state_cap: int = 400_000

    def __init__(self, specs: Sequence[ProtocolSpec]) -> None:
        self.specs = {s.id: s for s in specs}

    def initial(self) -> tuple:
        raise NotImplementedError

    def successors(self, s: tuple) -> Iterable[Succ]:
        raise NotImplementedError

    def invariant(self, s: tuple) -> Optional[str]:
        """None = fine; else the violated-invariant message."""
        return None

    def counters(self, s: tuple) -> Dict[str, int]:
        return {}

    def proj(self, s: tuple) -> Dict[Tuple[str, str, Optional[str]], Optional[str]]:
        """(spec_id, machine, entity) -> machine state, None = the
        machine instance does not exist in `s` (creation/deletion is
        not an edge)."""
        return {}

    def liveness(self) -> Tuple[Tuple[str, Callable, Callable], ...]:
        """(obligation id, applies(state), goal(state)) triples: every
        reachable state satisfying `applies` must reach a `goal`
        state."""
        return ()


# ---------------------------------------------------------------------------
# breaker: closed -> open -> half-open
# ---------------------------------------------------------------------------
class BreakerModel(Model):
    """CircuitConfig scope: failure_threshold=2, half_open_probes=1.
    State: (state, consecutive_failures, probes, backoff_elapsed)."""

    name = "breaker"
    T, P = 2, 1
    covered = (("breaker", "breaker"),)
    expect_max = {"half_open_probes_admitted": 1}

    def initial(self) -> tuple:
        return ("closed", 0, 0, 0)

    def _e(self, eid: str) -> Tuple[EdgeRef, ...]:
        return (("breaker", "breaker", eid, None),)

    def successors(self, s: tuple) -> Iterable[Succ]:
        st, cf, probes, elapsed = s
        ncf = min(cf + 1, self.T)
        if st == "closed":
            if ncf >= self.T:
                yield ("fail:trip", self._e("trip"), ("open", ncf, 0, 0))
            else:
                yield ("fail", (), ("closed", ncf, probes, elapsed))
        elif st == "half_open":
            yield (
                "fail:probe_failed", self._e("reopen_probe_fail"),
                ("open", ncf, 0, 0),
            )
        else:  # OPEN: straggler failures neither extend nor double-trip
            yield ("fail:straggler", (), ("open", ncf, probes, elapsed))
        if st == "closed":
            if cf:
                yield ("success", (), ("closed", 0, probes, elapsed))
        else:
            yield ("success:close", self._e("close"), ("closed", 0, 0, 0))
        if st == "open" and not elapsed:
            yield ("tick:backoff_expires", (), ("open", cf, probes, 1))
        if st == "open" and elapsed:
            # allow() flips to HALF_OPEN and consumes the probe token.
            yield (
                "allow:probe", self._e("half_open_entry"),
                ("half_open", cf, 1, 0),
            )
        if st == "half_open" and probes >= self.P:
            yield (
                "tick:probe_timeout", self._e("reopen_probe_abandoned"),
                ("open", cf, 0, 0),
            )

    def invariant(self, s: tuple) -> Optional[str]:
        st, _cf, probes, _elapsed = s
        if probes > self.P:
            return (
                f"{probes} probes admitted in one half-open episode "
                f"(> half_open_probes={self.P})"
            )
        if st != "half_open" and probes and st == "open":
            return "probe tokens outstanding while OPEN"
        return None

    def counters(self, s: tuple) -> Dict[str, int]:
        return {"half_open_probes_admitted": s[2]}

    def proj(self, s: tuple) -> Dict[Tuple[str, str, Optional[str]], Optional[str]]:
        return {("breaker", "breaker", None): s[0]}

    def liveness(self) -> Tuple[Tuple[str, Callable, Callable], ...]:
        return (
            (
                "breaker-reprobes",
                lambda s: s[0] == "open",
                lambda s: s[0] == "half_open",
            ),
            (
                "breaker-recloses",
                lambda s: True,
                lambda s: s[0] == "closed",
            ),
        )


# ---------------------------------------------------------------------------
# lease: grant/renew/reconcile/release/expire
# ---------------------------------------------------------------------------
class LeaseModel(Model):
    """LeaseConfig scope: limit L=4, fraction 1/4 (allowance a=1),
    max_holders H=2, two clients.  State:
    ((hv, local) per client, slot_rem, auth_rem, unreconciled, admitted)
    where hv is the owner's holder record (A absent / R reserved /
    V active) and `local` is the holder's unspent local allowance —
    kept across owner-side expiry: a partitioned holder burns its full
    unreconciled grant, the bound's worst case."""

    name = "lease"
    L, H, A = 4, 2, 1
    SLOT = H * A  # the carve slot's per-window allowance budget
    covered = (("lease", "holders"),)
    expect_max = {"admitted": 6}  # L * (1 + H * fraction)

    def initial(self) -> tuple:
        return ((("A", 0), ("A", 0)), self.SLOT, self.L, 0, 0)

    def _e(self, eid: str, c: int) -> Tuple[EdgeRef, ...]:
        return (("lease", "holders", eid, f"c{c}"),)

    def successors(self, s: tuple) -> Iterable[Succ]:
        holders, slot, auth, unrec, adm = s
        nonabsent = sum(1 for hv, _l in holders if hv != "A")

        def with_holder(i: int, hv: str, loc: int) -> tuple:
            hs = list(holders)
            hs[i] = (hv, loc)
            return tuple(hs)

        for i, (hv, loc) in enumerate(holders):
            if hv == "A" and nonabsent < self.H:
                yield (
                    f"grant:reserve:c{i}", self._e("reserve", i),
                    (with_holder(i, "R", loc), slot, auth, unrec, adm),
                )
            if hv == "R":
                if slot >= self.A:
                    yield (
                        f"grant:fill:c{i}", self._e("fill", i),
                        (with_holder(i, "V", self.A), slot - self.A,
                         auth, unrec, adm),
                    )
                # Carve refused (device error / allowance exhausted):
                # the placeholder is dropped either way.
                yield (
                    f"grant:refuse:c{i}", self._e("unreserve", i),
                    (with_holder(i, "A", loc), slot, auth, unrec, adm),
                )
            if hv == "V":
                yield (
                    f"reconcile:release:c{i}", self._e("release", i),
                    (with_holder(i, "A", 0), slot, auth, unrec, adm),
                )
                # Expiry keeps the holder's local allowance: the
                # partitioned holder never saw the sweep.
                yield (
                    f"sweep:expire:c{i}", self._e("expire", i),
                    (with_holder(i, "A", loc), slot, auth, unrec, adm),
                )
            if loc > 0:
                yield (
                    f"burn:c{i}", (),
                    (with_holder(i, hv, loc - 1), slot, auth,
                     min(unrec + 1, self.SLOT), adm + 1),
                )
        if auth > 0:
            yield (
                "serve:direct", (),
                (holders, slot, auth - 1, unrec, adm + 1),
            )
        if unrec > 0:
            # queue_hit flush: converges the row, admits nothing.
            yield (
                "reconcile:burned_hits", (),
                (holders, slot, max(auth - 1, 0), unrec - 1, adm),
            )

    def invariant(self, s: tuple) -> Optional[str]:
        adm = s[4]
        bound = self.L + self.SLOT
        if adm > bound:
            return (
                f"admitted {adm} > limit x (1 + max_holders x fraction)"
                f" = {bound}"
            )
        return None

    def counters(self, s: tuple) -> Dict[str, int]:
        return {"admitted": s[4]}

    def proj(self, s: tuple) -> Dict[Tuple[str, str, Optional[str]], Optional[str]]:
        names = {"A": "absent", "R": "reserved", "V": "active"}
        return {
            ("lease", "holders", f"c{i}"): names[hv]
            for i, (hv, _l) in enumerate(s[0])
        }

    def liveness(self) -> Tuple[Tuple[str, Callable, Callable], ...]:
        return ((
            "lease-collected",
            lambda s: any(hv != "A" for hv, _l in s[0]),
            lambda s: all(hv == "A" for hv, _l in s[0]),
        ),)


# ---------------------------------------------------------------------------
# reshard: PREPARE -> DRAIN -> TRANSFER -> CUTOVER -> RELEASE
# ---------------------------------------------------------------------------
# The reshard sub-state shared with the composition model:
#   (ob, ib, row, rowA, sh, led, fresh, frem, snap)
#   ob   outbound phase at the old owner A
#   ib   inbound record at the new owner B: none/prepare/transfer/done
#   row  where the moved row is: old / wire / new / lost
#   rowA the row's remaining budget (follows it)
#   sh   handoff-shadow remaining; led: shadow burns awaiting cutover
#   fresh/frem  self-cutover created a fresh row at B (lost rows reset)
#   snap wire snapshot of rowA at extract (broken replay variant only)
_TERMINAL_OB = ("released", "aborted")


def _reshard_succs(
    rs: tuple, L: int, replay_guard: bool
) -> Iterable[Tuple[str, Tuple[EdgeRef, ...], tuple, int]]:
    """Yields (label, edges, next reshard sub-state, admitted delta)."""
    ob, ib, row, rowA, sh, led, fresh, frem, snap = rs

    def nxt(**kw: object) -> tuple:
        d = dict(
            ob=ob, ib=ib, row=row, rowA=rowA, sh=sh, led=led,
            fresh=fresh, frem=frem, snap=snap,
        )
        d.update(kw)
        return (
            d["ob"], d["ib"], d["row"], d["rowA"], d["sh"], d["led"],
            d["fresh"], d["frem"], d["snap"],
        )

    def e_out(eid: str) -> EdgeRef:
        return ("reshard", "outbound", eid, None)

    def e_in(eid: str) -> EdgeRef:
        return ("reshard", "inbound", eid, None)

    if ib == "none" and ob == "prepare":
        yield ("rpc:prepare", (), nxt(ib="prepare"), 0)
    if ob == "prepare":
        if ib == "prepare":
            yield ("ack:prepare", (e_out("prepare_ack"),), nxt(ob="drain"), 0)
        yield ("fault:prepare_fail", (e_out("abort"),), nxt(ob="aborted"), 0)
    if ob == "drain":
        if ib == "prepare":
            # One RPC fires both sides: the TRANSFER announcement lands
            # at B before A's extract+clear.
            yield (
                "rpc:transfer",
                (e_out("transfer_announce"), e_in("ib_transfer")),
                nxt(ob="transfer", ib="transfer"), 0,
            )
        yield ("fault:transfer_fail", (e_out("abort"),), nxt(ob="aborted"), 0)
    if ob == "transfer":
        if row == "old":
            yield (
                "extract", (),
                nxt(row="wire", snap=rowA if not replay_guard else 0), 0,
            )
        if row == "wire":
            if ib == "transfer":
                yield ("deliver", (), nxt(row="new"), 0)
            yield (
                "fault:chunk_lost", (e_out("abort"),),
                nxt(ob="aborted", row="lost"), 0,
            )
        if row == "new":
            yield ("shipped", (e_out("rows_shipped"),), nxt(ob="cutover"), 0)
            if not replay_guard and ib in ("transfer", "done"):
                # BROKEN: re-delivered chunk re-injects over the live
                # row, clobbering consumption back to the wire snapshot.
                yield ("fault:dup_migrate", (), nxt(rowA=snap), 0)
    if ob == "cutover":
        if ib == "transfer":
            yield (
                "rpc:cutover", (e_out("release"),),
                nxt(ob="released", ib="done",
                    rowA=max(0, rowA - led), led=0, sh=0), 0,
            )
        if ib == "done":
            # Idempotent-accept: the watchdog finalized first; the
            # sender only needs to know it may release.
            yield ("rpc:cutover_idem", (e_out("release"),), nxt(ob="released"), 0)
        yield ("fault:cutover_fail", (e_out("abort"),), nxt(ob="aborted"), 0)
    if ib in ("prepare", "transfer"):
        if row == "new":
            yield (
                "watchdog:self_cutover", (),
                nxt(ib="done", rowA=max(0, rowA - led), led=0, sh=0), 0,
            )
        else:
            # Rows that never arrived start fresh: conservative reset,
            # <= limit, never inflated.
            yield (
                "watchdog:self_cutover", (),
                nxt(ib="done", fresh=1, frem=L, led=0, sh=0), 0,
            )
    # -- serving ---------------------------------------------------------
    if row == "old" and ib in ("none", "prepare") and rowA > 0:
        # A is still authoritative: B forwards covered checks back
        # (or the check landed at A directly).
        yield ("serve:forward_back", (), nxt(rowA=rowA - 1), 1)
    if row == "old" and ob == "aborted" and rowA > 0:
        # Aborted pre-extract: A still holds the row and serves
        # stale-routed checks.
        yield ("serve:stale_old", (), nxt(rowA=rowA - 1), 1)
    if ib in ("prepare", "transfer") and sh > 0:
        # The window's entire double-admission budget.
        yield (
            "serve:shadow", (), nxt(sh=sh - 1, led=min(led + 1, 1)), 1,
        )
    if ib == "done":
        if fresh and frem > 0:
            yield ("serve:fresh", (), nxt(frem=frem - 1), 1)
        elif not fresh and row == "new" and rowA > 0:
            yield ("serve:new_owner", (), nxt(rowA=rowA - 1), 1)


class ReshardModel(Model):
    """ReshardConfig scope: one moved key, L=4, handoff_fraction=1/4
    (shadow limit 1), old owner A -> new owner B.
    State: (*reshard sub-state, admitted)."""

    name = "reshard"
    L, SHADOW = 4, 1
    covered = (("reshard", "outbound"), ("reshard", "inbound"))
    expect_max = {"admitted_clean": 5, "admitted_lost": 9}

    def __init__(self, specs, replay_guard: bool = True) -> None:
        super().__init__(specs)
        self.replay_guard = replay_guard
        if not replay_guard:
            self.name = "reshard-no-replay-guard"

    def initial(self) -> tuple:
        return ("prepare", "none", "old", self.L, self.SHADOW, 0, 0, 0, 0, 0)

    def successors(self, s: tuple) -> Iterable[Succ]:
        rs, adm = s[:9], s[9]
        for label, edges, nrs, dadm in _reshard_succs(
            rs, self.L, self.replay_guard
        ):
            yield (label, edges, nrs + (adm + dadm,))

    def _budget(self, s: tuple) -> int:
        fresh = s[6]
        return self.L + self.SHADOW + (self.L if fresh else 0)

    def invariant(self, s: tuple) -> Optional[str]:
        ob, ib, row, rowA, sh, led, fresh, frem, _snap, adm = s
        budget = self._budget(s)
        if adm > budget:
            kind = "2L + f*L (rows lost)" if fresh else "L x (1 + f)"
            return f"admitted {adm} > {kind} = {budget}"
        live = (rowA if row != "lost" else 0) + sh + frem
        if adm + live > budget:
            return (
                f"row inflated: admitted {adm} + live budget {live} > "
                f"{budget} (conservation: applying burns or injecting "
                "can only lower remaining)"
            )
        return None

    def counters(self, s: tuple) -> Dict[str, int]:
        fresh, adm = s[6], s[9]
        return {
            "admitted_clean": 0 if fresh else adm,
            "admitted_lost": adm if fresh else 0,
        }

    def proj(self, s: tuple) -> Dict[Tuple[str, str, Optional[str]], Optional[str]]:
        ob, ib = s[0], s[1]
        return {
            ("reshard", "outbound", None): ob,
            ("reshard", "inbound", None): (
                ib if ib in ("prepare", "transfer") else None
            ),
        }

    def liveness(self) -> Tuple[Tuple[str, Callable, Callable], ...]:
        return (
            (
                "reshard-outbound-terminates",
                lambda s: s[0] not in _TERMINAL_OB,
                lambda s: s[0] in _TERMINAL_OB,
            ),
            (
                "reshard-inbound-finalizes",
                lambda s: s[1] in ("prepare", "transfer"),
                lambda s: s[1] == "done",
            ),
        )


# ---------------------------------------------------------------------------
# tier: hot -> demote -> cold -> promote
# ---------------------------------------------------------------------------
class TierModel(Model):
    """TierConfig scope: one key, L=4, at most 2 demote(-or-restore)/
    promote cycles.  State:
    (loc, hot_rem, cold_rem, fresh_consumed, cycles, admitted) —
    `fresh_consumed` counts hits served from the fresh row while the
    key is cold-resident (the pre-promote window); migrate_inject
    merges by subtracting it from the cold row, clamped at zero."""

    name = "tier"
    L, CYCLES = 4, 2
    covered = (("tier", "residency"),)
    expect_max = {"admitted": 12}  # L * (1 + CYCLES)

    def initial(self) -> tuple:
        return ("hot", self.L, 0, 0, 0, 0)

    def _e(self, eid: str) -> Tuple[EdgeRef, ...]:
        return (("tier", "residency", eid, None),)

    def successors(self, s: tuple) -> Iterable[Succ]:
        loc, hot, cold, fc, cyc, adm = s
        if loc == "hot":
            if hot > 0:
                yield ("serve:hot", (), ("hot", hot - 1, cold, fc, cyc, adm + 1))
            if cyc < self.CYCLES:
                yield (
                    "tick:demote", self._e("demote"),
                    ("cold", 0, hot, 0, cyc + 1, adm),
                )
                # A restart re-inserting the checkpoint's cold rows
                # widens admission exactly like a demote.
                yield (
                    "checkpoint:restore", self._e("restore"),
                    ("cold", 0, hot, 0, cyc + 1, adm),
                )
        if loc == "cold":
            if fc < self.L:
                # Cold-resident key served from a fresh row; the NEXT
                # round sees the merged history.
                yield (
                    "serve:cold_miss", (),
                    ("cold", hot, cold, fc + 1, cyc, adm + 1),
                )
            yield (
                "promote:inject", self._e("promote"),
                ("hot", max(0, cold - fc), 0, 0, cyc, adm),
            )
            # Inject failed twice -> rows conserved back to cold.
            yield ("promote:conserve", self._e("promote_conserve"), s)
            yield (
                "tick:prune_expired", self._e("prune"),
                ("dropped", 0, 0, fc, cyc, adm),
            )

    def invariant(self, s: tuple) -> Optional[str]:
        _loc, _hot, _cold, _fc, cyc, adm = s
        bound = self.L * (1 + cyc)
        if adm > bound:
            return (
                f"admitted {adm} > limit x (1 + {cyc} demote/promote "
                f"cycles) = {bound}"
            )
        return None

    def counters(self, s: tuple) -> Dict[str, int]:
        return {"admitted": s[5]}

    def proj(self, s: tuple) -> Dict[Tuple[str, str, Optional[str]], Optional[str]]:
        return {("tier", "residency", None): s[0]}

    def liveness(self) -> Tuple[Tuple[str, Callable, Callable], ...]:
        return ((
            "tier-promotes",
            lambda s: s[0] == "cold",
            lambda s: s[0] in ("hot", "dropped"),
        ),)


# ---------------------------------------------------------------------------
# composition: a remap strikes an owner with outstanding leases
# ---------------------------------------------------------------------------
class ReshardLeaseModel(Model):
    """The composition the algebra must close over: the demoted owner A
    holds outstanding lease grants when the ring remaps the key to B.
    A's LeaseManager revokes its records (drop_unowned), but partitioned
    holders keep burning their unreconciled local allowance — the lease
    bound's worst case — while the handoff window adds its shadow carve.

    Scope: L=4, H=2 holders at allowance 1 each, handoff shadow 1.
    State: (holders, *reshard sub-state, admitted); each holder is
    U (never granted) / G (granted, allowance unspent) / B (burned)."""

    name = "reshard_lease"
    L, H, SHADOW = 4, 2, 1
    covered = ()  # bounds composition; edge coverage rides the per-plane models
    expect_max = {"admitted_clean": 7, "admitted_lost": 11}
    state_cap = 600_000

    def initial(self) -> tuple:
        return (
            ("U", "U"),
            "idle", "none", "old", self.L, self.SHADOW, 0, 0, 0,
            0,
        )

    def successors(self, s: tuple) -> Iterable[Succ]:
        holders, adm = s[0], s[9]
        rs = s[1:9] + (0,)  # snap unused (replay guard on)
        ob = rs[0]

        def with_holder(i: int, hv: str) -> tuple:
            hs = list(holders)
            hs[i] = hv
            return tuple(hs)

        for i, hv in enumerate(holders):
            if hv == "U" and ob == "idle":
                # Grants only while A is the undisturbed owner: the
                # remap revokes records and refuses new grants
                # (refusal_for: "not the owner of this key").
                yield (
                    f"grant:c{i}", (),
                    (with_holder(i, "G"),) + s[1:9] + (adm,),
                )
            if hv == "G":
                # The partitioned holder burns with zero RPCs — before
                # or after the remap, reconciled or not.
                yield (
                    f"burn:c{i}", (),
                    (with_holder(i, "B"),) + s[1:9] + (adm + 1,),
                )
        if ob == "idle":
            rowA = s[4]
            if rowA > 0:
                yield (
                    "serve:owner", (),
                    (holders,) + ("idle",) + s[2:4] + (rowA - 1,)
                    + s[5:9] + (adm + 1,),
                )
            yield (
                "remap:start", (),
                (holders, "prepare") + s[2:9] + (adm,),
            )
        else:
            for label, edges, nrs, dadm in _reshard_succs(
                rs, self.L, True
            ):
                yield (
                    label, edges,
                    (holders,) + nrs[:8] + (adm + dadm,),
                )

    def invariant(self, s: tuple) -> Optional[str]:
        holders, adm = s[0], s[9]
        row, rowA, sh, fresh, frem = s[3], s[4], s[5], s[7], s[8]
        budget = self.L + self.H + self.SHADOW + (self.L if fresh else 0)
        if adm > budget:
            kind = (
                "L x (1 + H*f + f_h) + L (rows lost)" if fresh
                else "L x (1 + H*f + f_h)"
            )
            return f"admitted {adm} > {kind} = {budget}"
        live = (
            (rowA if row != "lost" else 0) + sh + frem
            + sum(1 for hv in holders if hv == "G")
        )
        if adm + live > budget:
            return (
                f"budget inflated: admitted {adm} + outstanding {live} "
                f"> {budget}"
            )
        return None

    def counters(self, s: tuple) -> Dict[str, int]:
        fresh, adm = s[7], s[9]
        return {
            "admitted_clean": 0 if fresh else adm,
            "admitted_lost": adm if fresh else 0,
        }

    def proj(self, s: tuple) -> Dict[Tuple[str, str, Optional[str]], Optional[str]]:
        ob, ib = s[1], s[2]
        return {
            ("reshard", "outbound", None): (
                ob if ob != "idle" else None
            ),
            ("reshard", "inbound", None): (
                ib if ib in ("prepare", "transfer") else None
            ),
        }

    def liveness(self) -> Tuple[Tuple[str, Callable, Callable], ...]:
        return ((
            "composition-quiesces",
            lambda s: s[1] not in ("idle",) + _TERMINAL_OB
            or any(hv == "G" for hv in s[0])
            or s[2] in ("prepare", "transfer"),
            lambda s: s[1] in ("idle",) + _TERMINAL_OB
            and not any(hv == "G" for hv in s[0])
            and s[2] in ("none", "done"),
        ),)


# ---------------------------------------------------------------------------
# region: carve serve / WAN reconcile / partition / rehome
# ---------------------------------------------------------------------------
class RegionModel(Model):
    """RegionConfig scope: R=2 regions, one key homed in the REMOTE
    region, L=4, region_fraction=1/4 (carve C=1).  This node's view:
    the home row's budget, the local carve slot, the reconcile
    backlog, and the link state machine.  State:
    (link, home_rem, carve_rem, pending, admitted).

    The exact closure: admitted == L x (1 + (R-1) x f) == 5, reached
    by draining both budgets, never exceeded because the carve slot
    is NEVER reset at cutover — `cutover_reset=True` restores the
    carve's allowance on every heal (the tempting-but-wrong
    compensation), and its counterexample (partition -> burn the
    carve -> heal -> burn again) is the second seeded chaos-plan
    fixture in tests/test_gubproof.py."""

    name = "region"
    L, C = 4, 1
    covered = (("region", "link"),)
    expect_max = {"admitted": 5}  # L * (1 + (R-1) * fraction)

    def __init__(self, specs, cutover_reset: bool = False) -> None:
        super().__init__(specs)
        self.cutover_reset = cutover_reset
        if cutover_reset:
            self.name = "region-cutover-reset"

    def initial(self) -> tuple:
        return ("remote", self.L, self.C, 0, 0)

    def _e(self, eid: str) -> Tuple[EdgeRef, ...]:
        return (("region", "link", eid, None),)

    def successors(self, s: tuple) -> Iterable[Succ]:
        link, home, carve, pending, adm = s
        if home > 0:
            # A check landing in the HOME region: full budget.
            yield (
                "serve:home", (),
                (link, home - 1, carve, pending, adm + 1),
            )
        if carve > 0 and link in ("remote", "degraded"):
            # A remote-homed check served from the local carve slot;
            # the admitted burn queues toward home.
            yield (
                "serve:carve", (),
                (link, home, carve - 1, pending + 1, adm + 1),
            )
        if pending > 0 and link == "remote":
            # The WAN reconcile cadence: the burn lands at home and
            # debits the authoritative row (admitting nothing — a
            # saturated row simply denies it).
            yield (
                "reconcile:flush", (),
                (link, max(0, home - 1), carve, pending - 1, adm),
            )
        if link == "remote":
            yield (
                "fault:partition", self._e("wan_lost"),
                ("degraded", home, carve, pending, adm),
            )
        if link == "degraded":
            yield (
                "rehome:heal", self._e("heal_prepare"),
                ("region_prepare", home, carve, pending, adm),
            )
        if link == "region_prepare":
            yield (
                "rehome:transfer", self._e("prepare_transfer"),
                ("transfer", home, carve, pending, adm),
            )
        if link == "transfer":
            if pending > 0:
                # The cutover compensation: late burns drain to home.
                yield (
                    "rehome:drain", (),
                    (link, max(0, home - 1), carve, pending - 1, adm),
                )
            else:
                yield (
                    "rehome:cutover", self._e("transfer_cutover"),
                    ("cutover", home, carve, pending, adm),
                )
            # The WAN can die again mid-transfer: abort to degraded.
            yield (
                "fault:partition", self._e("wan_lost"),
                ("degraded", home, carve, pending, adm),
            )
        if link == "cutover":
            ncarve = self.C if self.cutover_reset else carve
            yield (
                "rehome:remote", self._e("cutover_remote"),
                ("remote", home, ncarve, pending, adm),
            )

    def invariant(self, s: tuple) -> Optional[str]:
        _link, home, carve, _pending, adm = s
        bound = self.L + self.C
        if adm > bound:
            return (
                f"admitted {adm} > limit x (1 + remote_regions x "
                f"region_fraction) = {bound}"
            )
        if adm + home + carve > bound:
            return (
                f"budget inflated: admitted {adm} + outstanding "
                f"{home + carve} > {bound} (a heal must not refresh "
                "the carve's window allowance)"
            )
        return None

    def counters(self, s: tuple) -> Dict[str, int]:
        return {"admitted": s[4]}

    def proj(self, s: tuple) -> Dict[Tuple[str, str, Optional[str]], Optional[str]]:
        return {("region", "link", None): s[0]}

    def liveness(self) -> Tuple[Tuple[str, Callable, Callable], ...]:
        return (
            (
                "region-link-reheals",
                lambda s: s[0] != "remote",
                lambda s: s[0] == "remote",
            ),
            (
                "region-drift-drains",
                lambda s: s[3] > 0,
                lambda s: s[3] == 0,
            ),
        )


# ---------------------------------------------------------------------------
# composition: the home region reshards while a remote region carves
# ---------------------------------------------------------------------------
class RegionReshardModel(Model):
    """Region rejoin rides the reshard machinery INSIDE the home
    region: while a remote region serves from its carve and reconciles
    over the WAN, the home region's ring remaps the key old owner A ->
    new owner B (handoff shadow and all).  The algebra must close over
    the sum: the home handoff budget plus the remote carve.

    Scope: L=4, handoff shadow 1, carve C=1.  State:
    (link, carve_rem, pending, *reshard sub-state, admitted).  WAN
    flushes debit whichever home budget is live (the row wherever the
    handoff moved it, or the fresh self-cutover row)."""

    name = "region_reshard"
    L, SHADOW, C = 4, 1, 1
    covered = ()  # bounds composition; edge coverage rides the per-plane models
    expect_max = {"admitted_clean": 6, "admitted_lost": 10}
    state_cap = 600_000

    def initial(self) -> tuple:
        return (
            "remote", self.C, 0,
            "prepare", "none", "old", self.L, self.SHADOW, 0, 0, 0, 0,
            0,
        )

    def _e(self, eid: str) -> Tuple[EdgeRef, ...]:
        return (("region", "link", eid, None),)

    @staticmethod
    def _debit_home(rs: tuple) -> tuple:
        """A reconciled burn lands in the home region and debits the
        live budget there: the fresh row after a lossy self-cutover,
        else the moved row wherever the handoff left it.  A saturated
        (or lost) row absorbs nothing — the burn is simply denied."""
        ob, ib, row, rowA, sh, led, fresh, frem, snap = rs
        if fresh:
            return (ob, ib, row, rowA, sh, led, fresh, max(0, frem - 1), snap)
        if row != "lost":
            return (ob, ib, row, max(0, rowA - 1), sh, led, fresh, frem, snap)
        return rs

    def successors(self, s: tuple) -> Iterable[Succ]:
        link, carve, pending = s[0], s[1], s[2]
        rs, adm = s[3:12], s[12]

        def pack(link=link, carve=carve, pending=pending, rs=rs, adm=adm):
            return (link, carve, pending) + rs + (adm,)

        if carve > 0 and link in ("remote", "degraded"):
            yield (
                "serve:carve", (),
                pack(carve=carve - 1, pending=pending + 1, adm=adm + 1),
            )
        if pending > 0 and link == "remote":
            yield (
                "reconcile:flush", (),
                pack(pending=pending - 1, rs=self._debit_home(rs)),
            )
        if link == "remote":
            yield (
                "fault:partition", self._e("wan_lost"),
                pack(link="degraded"),
            )
        if link == "degraded":
            yield ("rehome:heal", self._e("heal_prepare"),
                   pack(link="region_prepare"))
        if link == "region_prepare":
            yield ("rehome:transfer", self._e("prepare_transfer"),
                   pack(link="transfer"))
        if link == "transfer":
            if pending > 0:
                yield (
                    "rehome:drain", (),
                    pack(pending=pending - 1, rs=self._debit_home(rs)),
                )
            else:
                yield ("rehome:cutover", self._e("transfer_cutover"),
                       pack(link="cutover"))
        if link == "cutover":
            # The slot keeps its consumed state — no per-heal refresh.
            yield ("rehome:remote", self._e("cutover_remote"),
                   pack(link="remote"))
        # The home region's handoff runs concurrently with all of it.
        for label, edges, nrs, dadm in _reshard_succs(rs, self.L, True):
            yield (label, edges, pack(rs=nrs, adm=adm + dadm))

    def invariant(self, s: tuple) -> Optional[str]:
        carve = s[1]
        row, rowA, sh, fresh, frem = s[5], s[6], s[7], s[9], s[10]
        adm = s[12]
        budget = self.L + self.SHADOW + self.C + (self.L if fresh else 0)
        if adm > budget:
            kind = (
                "L x (1 + f_h) + f_R x L + L (rows lost)" if fresh
                else "L x (1 + f_h) + f_R x L"
            )
            return f"admitted {adm} > {kind} = {budget}"
        live = (rowA if row != "lost" else 0) + sh + frem + carve
        if adm + live > budget:
            return (
                f"budget inflated: admitted {adm} + outstanding {live} "
                f"> {budget}"
            )
        return None

    def counters(self, s: tuple) -> Dict[str, int]:
        fresh, adm = s[9], s[12]
        return {
            "admitted_clean": 0 if fresh else adm,
            "admitted_lost": adm if fresh else 0,
        }

    def proj(self, s: tuple) -> Dict[Tuple[str, str, Optional[str]], Optional[str]]:
        link, ob, ib = s[0], s[3], s[4]
        return {
            ("region", "link", None): link,
            ("reshard", "outbound", None): ob,
            ("reshard", "inbound", None): (
                ib if ib in ("prepare", "transfer") else None
            ),
        }

    def liveness(self) -> Tuple[Tuple[str, Callable, Callable], ...]:
        return ((
            "region-reshard-quiesces",
            lambda s: s[0] != "remote" or s[2] > 0
            or s[3] not in _TERMINAL_OB,
            lambda s: s[0] == "remote" and s[2] == 0
            and s[3] in _TERMINAL_OB,
        ),)


def build_models(specs: Sequence[ProtocolSpec]) -> List[Model]:
    """The default exploration set: one model per plane spec present,
    plus the compositions when both of their specs are."""
    ids = {s.id for s in specs}
    out: List[Model] = []
    if "breaker" in ids:
        out.append(BreakerModel(specs))
    if "lease" in ids:
        out.append(LeaseModel(specs))
    if "reshard" in ids:
        out.append(ReshardModel(specs))
    if "tier" in ids:
        out.append(TierModel(specs))
    if "region" in ids:
        out.append(RegionModel(specs))
    if "reshard" in ids and "lease" in ids:
        out.append(ReshardLeaseModel(specs))
    if "region" in ids and "reshard" in ids:
        out.append(RegionReshardModel(specs))
    return out
