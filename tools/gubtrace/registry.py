"""The declarative kernel registry: every jitted hot-path entrypoint.

Each spec names one jitted kernel, how to build canonical concrete
arguments for it (a small shape/dtype matrix — CPU-runnable sizes, the
invariants are shape-independent), which int64 inputs are tainted
counters/timestamps, the declared tainted-cast budget, the declared
donation surface, and the declared recompile budget.  The registry IS
the contract: a kernel change that moves any of these numbers must
change this file (or the golden snapshots) in the same PR, where a
reviewer sees it.

Canonical geometry (tiny on purpose — gubtrace runs under
JAX_PLATFORMS=cpu in CI):

  single-device   4096 slots x 8 ways, batches 64 and 128
  mesh            8 shards (the CI virtual-device count), 512
                  slots/shard, batch 64 per shard
  sketch          depth 4 x width 1024, batch 128

Counter patterns match `jax.tree_util.keystr` of the flattened args —
`.remaining` hits SlotTable.remaining (and .remaining_f, whose float
lineage the taint walk ignores by construction), `[2]` hits the bare
`now` argument.

Declared-cast budgets cite the deliberate conversion they license; the
dtype checker fails on the budget+1'th cast with its source line.

The pipelined drain (docs/pipeline.md) deliberately adds NO kernels:
its dispatch/fetch split is host-side orchestration over the
entrypoints already registered here (apply_batch_packed_q,
sharded_step_packed, sketch_multi_step, global_sync_step, the gather/
probe ops), so the golden primitive budgets are unchanged — the
completeness checker (AST scan for module-level jax.jit) stays the
authority that any future chained-dispatch kernel must land in this
file.

The lease plane (docs/leases.md) likewise adds NO kernels: grants,
reconciles, and carve-slot drops are host/client-side orchestration
whose device work is ordinary checks through the already-registered
step entrypoints (the `.lease-grant` slot is a normal table row), so
the 20 verified kernels and their goldens are unchanged.

The reshard plane (docs/resharding.md) adds TWO kernels in
ops/state.py: migrate_extract (gather+clear fused — the atomic
old-owner extraction) and migrate_inject (upsert-if-absent — the
new-owner injection that can never clobber newer state).  The mesh
backend's migration path deliberately adds none: it rides the
registered sharded gather/load kernels through the generic
PersistenceHost helpers.

Megaround serving (docs/ring.md) adds TWO kernels: mega_ring_step
(ops/ring.py — the scan OF the ring scan) and persistent_serve_step
(ops/pallas/serve_kernel.py — the persistent decision kernel, traced
through the interpret shim like cms_step_pallas).  The mesh megaround
lift (parallel/sharded.make_mesh_mega_ring_step) deliberately adds
none: it is the same shard_map composition mesh_ring_step already
verifies, over the registered mega body — a factory, not a
module-level jit, so the completeness checker's contract is unchanged.
"""
from __future__ import annotations

import functools
from typing import Callable, List

import numpy as np

from tools.gubtrace.core import BuiltKernel, KernelSpec

SLOTS = 4096
WAYS = 8
N_SHARDS = 8
MESH_B = 64
SKETCH_DEPTH = 4
SKETCH_WIDTH = 1024
SKETCH_B = 128

# Table int64 counter/timestamp columns (SlotTable has 12 leaves; the
# int32 enums algo/kind/status and the float remaining_f are excluded —
# their contracts bound them).
_TABLE_COUNTERS = (
    ".key", ".limit", ".duration", ".remaining", ".t0", ".burst",
    ".expire_at", ".touched",
)
_BATCH_COUNTERS = (
    ".key_hash", ".hits", ".greg_expire", ".greg_duration",
)


def _table():
    from gubernator_tpu.ops.state import init_table

    return init_table(SLOTS)


def _now():
    return np.int64(0)


def _device_batch(B: int):
    from gubernator_tpu.ops.step import DeviceBatchJ

    z64 = lambda: np.zeros(B, np.int64)  # noqa: E731
    zb = lambda: np.zeros(B, bool)  # noqa: E731
    return DeviceBatchJ(
        key_hash=z64(), hits=z64(), limit=z64(), duration=z64(),
        algo=np.zeros(B, np.int32), burst=z64(), reset_remaining=zb(),
        is_greg=zb(), greg_expire=z64(), greg_duration=z64(),
        active=zb(), use_cached=zb(),
    )


def _bucket_rows(B: int):
    from gubernator_tpu.ops.step import BucketRows

    z64 = lambda: np.zeros(B, np.int64)  # noqa: E731
    return BucketRows(
        key_hash=z64(), algo=np.zeros(B, np.int32), limit=z64(),
        duration=z64(), remaining=z64(),
        remaining_f=np.zeros(B, np.float64), t0=z64(),
        status=np.zeros(B, np.int32), burst=z64(), expire_at=z64(),
    )


def _cached_rows(B: int):
    from gubernator_tpu.ops.step import CachedRows

    z64 = lambda: np.zeros(B, np.int64)  # noqa: E731
    return CachedRows(
        key_hash=z64(), algo=np.zeros(B, np.int32), limit=z64(),
        remaining=z64(), status=np.zeros(B, np.int32), reset_time=z64(),
    )


def _step_spec(
    name: str,
    fn_name: str,
    impl_name: str,
    make_rest: Callable[[int], tuple],
    counters: tuple,
    allowed_casts: dict,
    donated: int,
    batches=(64, 128),
) -> KernelSpec:
    """Shared shape for the ops/step.py table kernels."""

    def build() -> BuiltKernel:
        import gubernator_tpu.ops.step as step

        fn = getattr(step, fn_name)
        impl = functools.partial(getattr(step, impl_name), ways=WAYS)

        def sig(B):
            return lambda: (_table(), *make_rest(B), _now())

        return BuiltKernel(
            fn=fn,
            trace_fn=impl,
            signatures={f"B{B}": sig(B) for B in batches},
            counters=counters,
            allowed_casts=allowed_casts,
            perturbations={
                # The caller-mistake replay: a python-scalar `now`
                # traces as a WEAK int64 and costs one extra compile.
                # Production callers pass np.int64 (runtime/backend);
                # this pins the cost of getting it wrong to exactly 1.
                "weak-now": lambda: (
                    _table(), *make_rest(batches[0]), 0
                ),
            },
            recompile_budget=len(batches) + 1,
            expect_aliased=donated,
        )

    return KernelSpec(name=name, where="gubernator_tpu/ops/step.py",
                      build=build)


# -- deliberate-cast budgets (ops/step.py) -------------------------------
# apply_batch taints every int64 table/batch counter.  The licensed
# casts are the leaky bucket's Go-float arithmetic — algorithms.go
# computes burst/rate/leak/hits in float64, re-derived here as the 14
# tainted `_f64(...)` sites in apply_batch_impl (lb0, lb1, l_rate x3,
# elapsed, lb4, ln_rate x2, ln_rem_f, plus the saturating ResetTime
# rewrite's f_now, f_lim and _f64(ln_resp_rem) — the reset product now
# runs in float64 through the _trunc_i64 saturation contract; each is
# exact below 2^53, the float64 mantissa).  The 15th would be a
# regression.
_APPLY_CASTS = {"to_f64": 14}
_APPLY_COUNTERS = _TABLE_COUNTERS + _BATCH_COUNTERS + (".limit",
                                                       ".duration", "[2]")
# Packed q-form: one widened-int64 row is narrowed back to the int32
# algo enum (values 0/1 by wire contract).
_APPLY_Q_CASTS = {"to_f64": 14, "to_i32": 1}


def _migrate_spec(name: str, fn_name: str, impl_name: str,
                  make_rest, counters, allowed_casts,
                  donated: int) -> KernelSpec:
    """ops/state.py live-migration kernels (docs/resharding.md): the
    extract is gather+clear in one donated dispatch (no licensed casts
    — the only conversions are widenings of the int32 enum columns into
    the packed int64 stack); the inject is probe+load+merge in one,
    with ONE licensed to_f64 — the conflict merge's leaky-bucket
    consumed budget (limit - remaining_f), exact below 2^53 like the
    step kernels' float sites."""

    def build() -> BuiltKernel:
        import gubernator_tpu.ops.state as state

        fn = getattr(state, fn_name)
        impl = functools.partial(getattr(state, impl_name), ways=WAYS)

        def sig(B):
            return lambda: (_table(), *make_rest(B), _now())

        return BuiltKernel(
            fn=fn,
            trace_fn=impl,
            signatures={f"B{B}": sig(B) for B in (64, 128)},
            counters=counters,
            allowed_casts=allowed_casts,
            perturbations={
                "weak-now": lambda: (_table(), *make_rest(64), 0),
            },
            recompile_budget=3,
            expect_aliased=donated,
        )

    return KernelSpec(name=name, where="gubernator_tpu/ops/state.py",
                      build=build)


def _table_stats_spec() -> KernelSpec:
    """ops/state.py table_stats: the gubstat one-pass state census
    (docs/observability.md) — occupancy, bucket-fill, slot-age / TTL
    histograms, per-algorithm remaining-fraction distribution, and the
    shadow-slot census over host-enumerated derived-key fingerprints.
    Read-only and NON-donated by contract (it dispatches against the
    live serving table as a ring host job); two licensed to_f64 casts
    (remaining and limit at the fraction site, exact below 2^53 like
    the step kernels' float sites — the f64->i32 bin index that
    follows rides converted float lineage, so it is not charged)."""

    def build() -> BuiltKernel:
        import gubernator_tpu.ops.state as state

        def sig(M: int):
            return lambda: (
                _table(), np.zeros((4, M), np.int64), _now()
            )

        return BuiltKernel(
            fn=state.table_stats,
            trace_fn=functools.partial(state.table_stats_impl, ways=WAYS),
            signatures={"M8": sig(8), "M16": sig(16)},
            counters=_TABLE_COUNTERS + ("[1]", "[2]"),
            allowed_casts={"to_f64": 2},
            perturbations={
                "weak-now": lambda: (
                    _table(), np.zeros((4, 8), np.int64), 0
                ),
            },
            recompile_budget=3,
            expect_aliased=0,
        )

    return KernelSpec(name="table_stats",
                      where="gubernator_tpu/ops/state.py", build=build)


def _mega_ring_spec() -> KernelSpec:
    """ops/ring.py mega_ring_step: megaround serving's scan OF the ring
    scan (docs/ring.md) — up to GUBER_RING_ROUNDS x GUBER_RING_SLOTS
    stacked rounds per dispatch.  The outer scan threads (table, seq)
    through ring_step_impl, so the taint and cast contract is exactly
    ring_step's (14 to_f64 leaky float sites + 1 to_i32 algo narrowing
    propagated through the nested scan carries); donation is table-only
    — the seq word's keep rule is inherited from the base ring."""

    def build() -> BuiltKernel:
        import gubernator_tpu.ops.ring as ring_mod

        def sig(r: int, s: int):
            return lambda: (
                _table(),
                np.zeros((r, s, 12, 64), np.int64),
                np.zeros((r, s), np.int64),
                np.zeros((), np.int64),
            )

        return BuiltKernel(
            fn=ring_mod.mega_ring_step,
            trace_fn=functools.partial(
                ring_mod.mega_ring_step_impl, ways=WAYS
            ),
            signatures={"r2s2": sig(2, 2), "r4s2": sig(4, 2)},
            counters=_TABLE_COUNTERS + ("[1]", "[2]", "[3]"),
            allowed_casts=dict(_APPLY_Q_CASTS),
            perturbations={
                # Caller-mistake replay: a python-int seq traces weak.
                "weak-seq": lambda: (
                    _table(), np.zeros((2, 2, 12, 64), np.int64),
                    np.zeros((2, 2), np.int64), 0,
                ),
            },
            recompile_budget=3,
            expect_aliased=12,  # table only — seq deliberately kept
        )

    return KernelSpec(
        name="mega_ring_step", where="gubernator_tpu/ops/ring.py",
        build=build,
    )


def _persistent_serve_spec() -> KernelSpec:
    """ops/pallas/serve_kernel.py persistent_serve_step: the persistent
    decision kernel — one Pallas launch drains the whole request queue
    with the table resident across grid steps (docs/ring.md).  Traced
    through the interpret shim like cms_step_pallas (Mosaic needs a
    real TPU; the interpret emulation is differentially pinned
    bit-exact against ring_step).  The decision body runs INSIDE the
    pallas_call, so the jaxpr-level cast walk sees only the wrapper's
    input normalization — zero licensed casts (the body's leaky float
    sites are covered where they are verified, on ring_step /
    apply_batch_packed_q); donation is table-only via the jit wrapper
    — the seq word rides the response queue un-donated, the ring keep
    rule."""

    def build() -> BuiltKernel:
        import gubernator_tpu.ops.pallas.serve_kernel as sk

        def sig(k: int):
            return lambda: (
                _table(),
                np.zeros((k, 12, 64), np.int64),
                np.zeros(k, np.int64),
                np.zeros((), np.int64),
            )

        return BuiltKernel(
            fn=_PallasInterpretShim(sk.persistent_serve_step),
            trace_fn=functools.partial(
                sk.persistent_serve_step_impl, ways=WAYS,
                interpret=True,
            ),
            signatures={"k1": sig(1), "k2": sig(2)},
            counters=_TABLE_COUNTERS + ("[1]", "[2]", "[3]"),
            allowed_casts={},
            perturbations={
                "weak-seq": lambda: (
                    _table(), np.zeros((1, 12, 64), np.int64),
                    np.zeros(1, np.int64), 0,
                ),
            },
            recompile_budget=3,
            expect_aliased=12,  # table only — seq deliberately kept
        )

    return KernelSpec(
        name="persistent_serve_step",
        where="gubernator_tpu/ops/pallas/serve_kernel.py",
        build=build,
    )


def _ring_spec() -> KernelSpec:
    """ops/ring.py ring_step: the ring discipline's bounded multi-round
    scan (docs/ring.md).  The scan body is apply_batch_packed_q traced
    once, so the int64 counter taint propagates through the lax.scan
    carry and the licensed casts are exactly the q-form step's (14
    to_f64 leaky float sites + 1 to_i32 algo narrowing); the sequence
    word is tainted int64 arithmetic with no cast.  Only the table is
    donated — the seq word's output buffer must survive the next
    iteration's dispatch (the double-buffered response protocol spins
    on it), so donating it would be a correctness bug, not a win."""

    def build() -> BuiltKernel:
        import gubernator_tpu.ops.ring as ring_mod

        def sig(k: int):
            return lambda: (
                _table(),
                np.zeros((k, 12, 64), np.int64),
                np.zeros(k, np.int64),
                np.zeros((), np.int64),
            )

        return BuiltKernel(
            fn=ring_mod.ring_step,
            trace_fn=functools.partial(ring_mod.ring_step_impl, ways=WAYS),
            signatures={"k1": sig(1), "k2": sig(2)},
            counters=_TABLE_COUNTERS + ("[1]", "[2]", "[3]"),
            allowed_casts=dict(_APPLY_Q_CASTS),
            perturbations={
                # Caller-mistake replay: a python-int seq traces weak.
                "weak-seq": lambda: (
                    _table(), np.zeros((1, 12, 64), np.int64),
                    np.zeros(1, np.int64), 0,
                ),
            },
            recompile_budget=3,
            expect_aliased=12,  # table only — seq deliberately kept
        )

    return KernelSpec(name="ring_step", where="gubernator_tpu/ops/ring.py",
                      build=build)


def _sketch_state():
    from gubernator_tpu.ops.sketch import init_sketch

    return init_sketch(SKETCH_DEPTH, SKETCH_WIDTH, window_ms=1000)


_SKETCH_COUNTERS = (".window_start", ".window_ms", "[1]", "[4]")
# row_columns narrows the multiply-shift hash to int32 bucket columns
# (< width <= 2^20) once per row; the window-overlap fraction is
# computed in f32 from the ms timestamps (bounded by window_ms).
_SKETCH_CASTS = {"to_i32": SKETCH_DEPTH, "to_f32": 2}


class _PallasInterpretShim:
    """cms_step_pallas with interpret=True pinned — jit facade for the
    execution-based checkers (donation/recompile) on CPU."""

    def __init__(self, jitted) -> None:
        self._jitted = jitted

    def __call__(self, *args):
        return self._jitted(*args, interpret=True)

    def lower(self, *args):
        return self._jitted.lower(*args, interpret=True)

    def clear_cache(self) -> None:
        self._jitted.clear_cache()

    def _cache_size(self) -> int:
        return self._jitted._cache_size()


def _sketch_spec(name: str, fn_name: str, impl_name: str) -> KernelSpec:
    def build() -> BuiltKernel:
        import gubernator_tpu.ops.sketch as sketch

        if fn_name == "cms_step_pallas":
            import gubernator_tpu.ops.pallas.cms_kernel as ck

            fn = ck.cms_step_pallas
            impl = ck.cms_step_pallas_impl
        else:
            fn = getattr(sketch, fn_name)
            impl = getattr(sketch, impl_name)

        def sig():
            return (
                _sketch_state(),
                np.zeros(SKETCH_B, np.int64),
                np.zeros(SKETCH_B, np.int32),
                np.zeros(SKETCH_B, np.int32),
                _now(),
            )

        def weak():
            return sig()[:4] + (0,)

        expect_aliased = 4
        if fn_name == "cms_step_pallas":
            # Mosaic needs a real TPU; interpret mode runs the same
            # semantics (differentially tested bit-exact) on CPU for
            # the execution-based checkers.
            fn = _PallasInterpretShim(ck.cms_step_pallas)

        return BuiltKernel(
            fn=fn,
            trace_fn=impl,
            signatures={"B128": sig},
            counters=_SKETCH_COUNTERS,
            allowed_casts=dict(_SKETCH_CASTS),
            perturbations={"weak-now": weak},
            recompile_budget=2,
            expect_aliased=expect_aliased,
        )

    where = (
        "gubernator_tpu/ops/pallas/cms_kernel.py"
        if fn_name == "cms_step_pallas" else "gubernator_tpu/ops/sketch.py"
    )
    return KernelSpec(name=name, where=where, build=build)


# -- mesh kernels --------------------------------------------------------

@functools.lru_cache(maxsize=1)
def _mesh():
    from gubernator_tpu.parallel.mesh import make_mesh

    return make_mesh(N_SHARDS)


def _sharded(arr_or_table, spec_dims):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(
        arr_or_table, NamedSharding(_mesh(), P(*spec_dims))
    )


def _mesh_table():
    from gubernator_tpu.ops.state import init_table

    return _sharded(init_table(SLOTS), ("shard",))


def _mesh_spec(
    name: str,
    factory: Callable,
    make_rest: Callable[[], tuple],
    counters: tuple,
    allowed_casts: dict,
    donated: int,
) -> KernelSpec:
    def build() -> BuiltKernel:
        fn = factory()

        def sig():
            return (_mesh_table(), *make_rest(), _now())

        return BuiltKernel(
            fn=fn,
            trace_fn=fn,
            signatures={f"n{N_SHARDS}xB{MESH_B}": sig},
            counters=counters,
            allowed_casts=allowed_casts,
            perturbations={},
            # One canonical signature; mesh callers always normalize
            # `now` (np.int64 at every call site), so no weak variant.
            recompile_budget=1,
            expect_aliased=donated,
        )

    return KernelSpec(name=name,
                      where="gubernator_tpu/parallel/sharded.py",
                      build=build)


def _packed_grid():
    return _sharded(
        np.zeros((12, N_SHARDS, MESH_B), np.int64), (None, "shard")
    )


def _row_grid(make_rows):
    rows = make_rows(N_SHARDS * MESH_B)
    return type(rows)(*[
        _sharded(np.asarray(a).reshape(N_SHARDS, MESH_B), ("shard",))
        for a in rows
    ])


def _hash_grid():
    return _sharded(np.zeros((N_SHARDS, MESH_B), np.int64), ("shard",))


def _delta_grid():
    from gubernator_tpu.parallel.global_sync import zero_delta_grid

    grid = zero_delta_grid(N_SHARDS, MESH_B)
    return type(grid)(*[_sharded(a, ("shard",)) for a in grid])


def _global_sync_spec(psum: bool = False) -> KernelSpec:
    def build() -> BuiltKernel:
        from gubernator_tpu.parallel.global_sync import (
            make_global_sync_step,
            make_global_sync_step_psum,
        )

        factory = make_global_sync_step_psum if psum else (
            make_global_sync_step
        )
        fn = factory(_mesh(), WAYS)

        def sig():
            return (_mesh_table(), _mesh_table(), _delta_grid(), _now())

        return BuiltKernel(
            fn=fn,
            trace_fn=fn,
            signatures={f"n{N_SHARDS}xD{MESH_B}": sig},
            counters=_TABLE_COUNTERS + _BATCH_COUNTERS + (
                ".limit", ".duration", "[3]",
            ),
            # Two apply_batch passes ride inside the sync step; the
            # broadcast re-read runs with hits=0 (a literal, untainted)
            # so its _f64(r_hits) does not count: 14 + 13.  The psum
            # form shares the budget — it swaps the aggregation
            # collective (one psum vs all_to_all + sort/segment), not
            # the apply passes.
            allowed_casts={"to_f64": 27},
            perturbations={},
            recompile_budget=1,
            expect_aliased=24,  # auth + cache tables, 12 leaves each
        )

    return KernelSpec(
        name="global_sync_step_psum" if psum else "global_sync_step",
        where="gubernator_tpu/parallel/global_sync.py",
        build=build,
    )


def _mesh_ring_spec() -> KernelSpec:
    """parallel/sharded.py make_mesh_ring_step: the ring discipline's
    bounded scan lifted to the sharded grid table (docs/ring.md).  Each
    shard runs ops/ring.ring_step_impl verbatim, so the taint and cast
    contract is exactly ring_step's (14 to_f64 leaky float sites + 1
    to_i32 algo narrowing propagated through the shard_map + scan
    carry); the per-shard sequence words are tainted int64 arithmetic
    with no cast.  Only the table is donated — the seq words' output
    buffers must survive the next iteration's dispatch (the
    double-buffered response protocol), exactly the single-device keep
    rule."""

    def build() -> BuiltKernel:
        from gubernator_tpu.parallel.sharded import make_mesh_ring_step

        fn = make_mesh_ring_step(_mesh(), WAYS)

        def sig(k: int):
            return lambda: (
                _mesh_table(),
                _sharded(
                    np.zeros((k, 12, N_SHARDS, MESH_B), np.int64),
                    (None, None, "shard"),
                ),
                np.zeros(k, np.int64),
                _sharded(np.zeros(N_SHARDS, np.int64), ("shard",)),
            )

        return BuiltKernel(
            fn=fn,
            trace_fn=fn,
            signatures={"k1": sig(1), "k2": sig(2)},
            counters=_TABLE_COUNTERS + ("[1]", "[2]", "[3]"),
            allowed_casts=dict(_APPLY_Q_CASTS),
            perturbations={},
            # Two slot tiers, mesh callers always normalize `now`
            # (np.int64 in ring_step_dispatch) — no weak variant.
            recompile_budget=2,
            expect_aliased=12,  # table only — per-shard seq kept
        )

    return KernelSpec(
        name="mesh_ring_step",
        where="gubernator_tpu/parallel/sharded.py",
        build=build,
    )


def _sketch_multi_spec() -> KernelSpec:
    def build() -> BuiltKernel:
        from gubernator_tpu.ops.sketch import cms_step_scatter_impl
        from gubernator_tpu.runtime.sketch_backend import make_multi_step

        fn = make_multi_step(cms_step_scatter_impl)

        def sig(k):
            return lambda: (
                _sketch_state(),
                np.zeros((k, SKETCH_B), np.int64),
                np.zeros((k, SKETCH_B), np.int32),
                np.zeros((k, SKETCH_B), np.int32),
                _now(),
            )

        return BuiltKernel(
            fn=fn,
            trace_fn=fn,
            signatures={"k1": sig(1), "k2": sig(2)},
            counters=_SKETCH_COUNTERS,
            allowed_casts=dict(_SKETCH_CASTS),
            perturbations={"weak-now": lambda: sig(1)()[:4] + (0,)},
            recompile_budget=3,
            expect_aliased=4,
        )

    return KernelSpec(
        name="sketch_multi_step",
        where="gubernator_tpu/runtime/sketch_backend.py",
        build=build,
    )


def specs() -> List[KernelSpec]:
    """Every registered kernel (build lazily; order = report order)."""

    def f_step(name):
        import gubernator_tpu.parallel.sharded as sh

        return {
            "sharded_step_packed":
                lambda: sh.make_sharded_step_packed(_mesh(), WAYS),
            "sharded_probe": lambda: sh.make_sharded_probe(_mesh(), WAYS),
            "sharded_gather":
                lambda: sh.make_sharded_gather(_mesh(), WAYS),
            "sharded_table_stats":
                lambda: sh.make_sharded_table_stats(_mesh(), WAYS),
        }[name]

    def row_factory(impl_name, row_type_name):
        def make():
            import gubernator_tpu.ops.step as step
            import gubernator_tpu.parallel.sharded as sh

            return sh.make_sharded_row_op(
                _mesh(), WAYS, getattr(step, impl_name),
                getattr(step, row_type_name),
            )

        return make

    def demote_factory():
        import gubernator_tpu.parallel.sharded as sh

        return sh.make_sharded_demote_extract(_mesh(), WAYS, MESH_B)

    return [
        # -- ops/step.py: the exact-tier table kernels ------------------
        _step_spec(
            "apply_batch", "apply_batch", "apply_batch_impl",
            lambda B: (_device_batch(B),),
            _APPLY_COUNTERS, dict(_APPLY_CASTS), donated=12,
        ),
        _step_spec(
            "load_rows", "load_rows", "load_rows_impl",
            lambda B: (_bucket_rows(B),),
            _TABLE_COUNTERS + (".key_hash", ".limit", ".duration", "[2]"),
            {}, donated=12,
        ),
        _step_spec(
            "probe_batch", "probe_batch", "probe_batch_impl",
            lambda B: (np.zeros(B, np.int64),),
            _TABLE_COUNTERS + ("[1]", "[2]"), {}, donated=0,
        ),
        _step_spec(
            "gather_rows", "gather_rows", "gather_rows_impl",
            lambda B: (np.zeros(B, np.int64),),
            _TABLE_COUNTERS + ("[1]", "[2]"), {}, donated=0,
        ),
        _step_spec(
            "store_cached_rows", "store_cached_rows",
            "store_cached_rows_impl",
            lambda B: (_cached_rows(B),),
            _TABLE_COUNTERS + (".key_hash", ".reset_time", "[2]"),
            {}, donated=12,
        ),
        _step_spec(
            "apply_batch_packed", "apply_batch_packed",
            "apply_batch_packed_impl",
            lambda B: (_device_batch(B),),
            _APPLY_COUNTERS, dict(_APPLY_CASTS), donated=12,
        ),
        _step_spec(
            "apply_batch_packed_q", "apply_batch_packed_q",
            "apply_batch_packed_q_impl",
            lambda B: (np.zeros((12, B), np.int64),),
            _TABLE_COUNTERS + ("[1]", "[2]"),
            dict(_APPLY_Q_CASTS), donated=12,
        ),
        # -- ops/state.py: live-migration row kernels -------------------
        _migrate_spec(
            "migrate_extract", "migrate_extract", "migrate_extract_impl",
            lambda B: (np.zeros(B, np.int64),),
            _TABLE_COUNTERS + ("[1]", "[2]"), {}, donated=12,
        ),
        _migrate_spec(
            "migrate_inject", "migrate_inject", "migrate_inject_impl",
            lambda B: (_bucket_rows(B),),
            _TABLE_COUNTERS + (".key_hash", ".limit", ".duration", "[2]"),
            {"to_f64": 1}, donated=12,
        ),
        # -- ops/state.py: the tier demotion kernel (docs/tiering.md) --
        # Same gather+clear atomicity shape as migrate_extract, but the
        # DEVICE names the victims: the B here sizes the replicated
        # protect grid; the packed batch rides the static default.
        _migrate_spec(
            "demote_extract", "demote_extract", "demote_extract_impl",
            lambda B: (np.zeros(B, np.int64),),
            _TABLE_COUNTERS + ("[1]", "[2]"), {}, donated=12,
        ),
        # -- ops/state.py: the gubstat state census ---------------------
        _table_stats_spec(),
        # -- ops/ring.py: the ring-fed device loop ----------------------
        _ring_spec(),
        _mega_ring_spec(),
        # -- ops/pallas/serve_kernel.py: the persistent decision kernel -
        _persistent_serve_spec(),
        # -- ops/sketch.py + the fused Pallas form ----------------------
        _sketch_spec("cms_step_onehot", "cms_step_onehot",
                     "cms_step_impl"),
        _sketch_spec("cms_step", "cms_step", "cms_step_scatter_impl"),
        _sketch_spec("cms_step_pallas", "cms_step_pallas",
                     "cms_step_pallas_impl"),
        # -- parallel/: the mesh engine ---------------------------------
        _mesh_spec(
            "sharded_step_packed", f_step("sharded_step_packed"),
            lambda: (_packed_grid(),),
            _TABLE_COUNTERS + ("[1]", "[2]"),
            dict(_APPLY_Q_CASTS), donated=12,
        ),
        _mesh_spec(
            "sharded_load_rows",
            row_factory("load_rows_impl", "BucketRows"),
            lambda: (_row_grid(_bucket_rows),),
            _TABLE_COUNTERS + (".key_hash", ".limit", ".duration", "[2]"),
            {}, donated=12,
        ),
        _mesh_spec(
            "sharded_store_cached",
            row_factory("store_cached_rows_impl", "CachedRows"),
            lambda: (_row_grid(_cached_rows),),
            _TABLE_COUNTERS + (".key_hash", ".reset_time", "[2]"),
            {}, donated=12,
        ),
        _mesh_spec(
            "sharded_probe", f_step("sharded_probe"),
            lambda: (_hash_grid(),),
            _TABLE_COUNTERS + ("[1]", "[2]"), {}, donated=0,
        ),
        _mesh_spec(
            "sharded_gather", f_step("sharded_gather"),
            lambda: (_hash_grid(),),
            _TABLE_COUNTERS + ("[1]", "[2]"), {}, donated=0,
        ),
        _mesh_spec(
            "sharded_demote_extract", demote_factory,
            lambda: (np.zeros(8, np.int64),),
            _TABLE_COUNTERS + ("[1]", "[2]"), {}, donated=12,
        ),
        _mesh_spec(
            "sharded_table_stats", f_step("sharded_table_stats"),
            lambda: (np.zeros((4, 8), np.int64),),
            _TABLE_COUNTERS + ("[1]", "[2]"),
            {"to_f64": 2}, donated=0,
        ),
        _mesh_ring_spec(),
        _global_sync_spec(),
        _global_sync_spec(psum=True),
        # -- runtime/sketch_backend.py: the merge-scan step -------------
        _sketch_multi_spec(),
    ]


def registered_names() -> List[str]:
    return [s.name for s in specs()]
