"""CLI: python -m tools.gubtrace [--select a,b] [--kernel name] [--update].

Must configure the platform BEFORE jax initializes: the verifier runs
device-free (JAX_PLATFORMS=cpu) on a virtual 8-device host platform so
the mesh kernels trace exactly as CI's virtual pod slice does.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _pin_cpu_platform() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def main(argv=None) -> int:
    _pin_cpu_platform()
    from pathlib import Path

    from tools.gubtrace import ALL_CHECKERS, run

    ap = argparse.ArgumentParser(
        prog="gubtrace",
        description=(
            "jaxpr-level static verification of every registered "
            "jitted kernel (see docs/gubtrace.md)."
        ),
    )
    ap.add_argument(
        "--select", metavar="NAMES",
        help="comma-separated checker subset of: " + ", ".join(ALL_CHECKERS),
    )
    ap.add_argument(
        "--kernel", action="append", metavar="NAME",
        help="restrict to this registered kernel (repeatable)",
    )
    ap.add_argument(
        "--update", action="store_true",
        help="regenerate the golden primitive-count snapshots",
    )
    ap.add_argument(
        "--list", action="store_true", dest="list_kernels",
        help="list registered kernels and exit",
    )
    ap.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings as a JSON array",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="treat warnings as errors",
    )
    ap.add_argument(
        "--root", default=".",
        help="repo root (default: cwd)",
    )
    ap.add_argument(
        "--dump-dir", default=None,
        help=(
            "where to write failing kernels' jaxpr dumps "
            "(default: $GUBTRACE_DUMP_DIR or gubtrace-dumps)"
        ),
    )
    args = ap.parse_args(argv)

    if args.list_kernels:
        from tools.gubtrace.registry import specs

        for s in specs():
            print(f"{s.name}  ({s.where})")
        return 0

    select = (
        [s.strip() for s in args.select.split(",") if s.strip()]
        if args.select else None
    )
    ctx_out: list = []
    findings = run(
        select=select,
        kernels=args.kernel,
        root=Path(args.root),
        update_golden=args.update,
        ctx_out=ctx_out,
    )

    if args.as_json:
        print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
    errors = [
        f for f in findings
        if f.severity == "error" or (args.strict and f.severity == "warning")
    ]
    warnings = [f for f in findings if f.severity == "warning"]

    if errors and ctx_out:
        # Jaxpr dumps for the failure artifact (CI uploads this dir).
        from gubernator_tpu.core.config import gubtrace_dump_dir_from_env

        dump_dir = Path(args.dump_dir or gubtrace_dump_dir_from_env())
        dump_dir.mkdir(parents=True, exist_ok=True)
        failing = {f.kernel for f in errors}
        for kernel, sigs in ctx_out[0].jaxprs.items():
            if kernel not in failing:
                continue
            for sig, jaxpr in sigs.items():
                p = dump_dir / f"{kernel}.{sig}.jaxpr.txt"
                p.write_text(str(jaxpr), encoding="utf-8")
        if not args.as_json:
            print(f"gubtrace: jaxpr dumps written to {dump_dir}/")

    if not args.as_json:
        n_k = len(ctx_out[0].jaxprs) if ctx_out else 0
        print(
            f"gubtrace: {n_k} kernel(s) verified, {len(errors)} "
            f"error(s), {len(warnings)} warning(s)"
        )
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
