"""recompile: jit cache misses must match the declared budget.

An XLA recompile on the serving path is a multi-second stall — a weak
-type leak (python-scalar `now` instead of `np.int64`), a new implicit
static, or a signature that fails to normalize turns into a recompile
*storm* that blows the p99 budget ("Designing Scalable Rate Limiting
Systems" puts tail latency at the center of limiter SLOs).  The audit
replays each kernel across its canonical signature matrix TWICE (a
second pass must be all cache hits), then applies the registry's
perturbed variants (python-scalar/weak-type `now`), and asserts the
jit cache entry count equals the declared budget — every cache miss is
accounted for, none are surprises.

Runs real executions on CPU at the canonical (tiny) shapes.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from tools.gubtrace.core import (
    BuiltKernel,
    Checker,
    Finding,
    KernelSpec,
    RunContext,
)


def cache_size(fn) -> Optional[int]:
    """Jit cache entry count, None when this jax build hides it."""
    try:
        return int(fn._cache_size())
    except Exception:
        return None


def runtime_cache_report() -> Dict[str, Optional[int]]:
    """Live jit-cache entry counts for every module-level jitted kernel
    the registry watches — the runtime counterpart of the static audit
    (`gubernator-tpu-microbench --recompile-audit` prints this after a
    canonical workload; a count above the expected tier/shape set means
    a recompile storm reached production)."""
    import importlib
    from pathlib import Path

    from tools.gubtrace.completeness import (
        WATCHED_MODULES,
        module_level_jits,
    )

    report: Dict[str, Optional[int]] = {}
    for rel in WATCHED_MODULES:
        modname = rel[:-3].replace("/", ".")
        mod = importlib.import_module(modname)
        source = Path(mod.__file__).read_text(encoding="utf-8")
        for name, _line in module_level_jits(source):
            fn = getattr(mod, name, None)
            if fn is not None:
                report[f"{modname}.{name}"] = cache_size(fn)
    return report


class RecompileChecker(Checker):
    name = "recompile"

    def check(self, spec: KernelSpec, built: BuiltKernel,
              ctx: RunContext) -> Iterable[Finding]:
        import jax

        fn = built.fn
        if built.recompile_budget is None:
            return ()
        try:
            fn.clear_cache()
        except Exception:
            pass
        start = cache_size(fn)
        if start is None:
            return [Finding(
                checker=self.name, kernel=spec.name, severity="warning",
                message="jit cache size not introspectable on this "
                        "jax build; audit skipped",
            )]
        out: List[Finding] = []
        # Donated buffers die on first use: rebuild args per pass.
        for passno in range(2):
            for sig_name, make_args in built.signatures.items():
                res = fn(*make_args())
                jax.block_until_ready(res)
            after = cache_size(fn) - start
            if passno == 0:
                first = after
            elif after != first:
                out.append(Finding(
                    checker=self.name, kernel=spec.name,
                    message=(
                        "replaying the canonical signatures added "
                        f"{after - first} cache entr(y/ies) — the "
                        "cache key is unstable (every production call "
                        "would recompile)"
                    ),
                ))
        for pname, make_args in built.perturbations.items():
            res = fn(*make_args())
            jax.block_until_ready(res)
        total = cache_size(fn) - start
        if total != built.recompile_budget:
            out.append(Finding(
                checker=self.name, kernel=spec.name,
                message=(
                    f"compilation-cache misses: observed {total}, "
                    f"declared {built.recompile_budget} "
                    f"({len(built.signatures)} canonical signatures + "
                    f"{len(built.perturbations)} perturbations) — an "
                    "unexpected miss is a recompile storm in "
                    "production; either normalize the input (preferred)"
                    " or re-declare the budget with a justification"
                ),
            ))
        return out
