"""gubtrace core: kernel specs, jaxpr walking, finding model, runner.

gubguard (tools/gubguard) checks what the Python *source* promises;
gubtrace checks what XLA will actually *compile*.  Every registered
jitted entrypoint (tools/gubtrace/registry.py) is traced with
`jax.make_jaxpr` over a canonical shape/dtype matrix — no accelerator
needed, the whole suite runs under `JAX_PLATFORMS=cpu` — and the closed
jaxprs are walked to enforce the device-side invariants:

  dtype-taint       no silent counter/timestamp dtype escapes
  host-escape       no callback primitives inside hot-path kernels
  donation          declared donate_argnums survive into the lowering
  primitive-budget  golden per-kernel counts of expensive primitives
  recompile         jit cache misses match the declared budget
  registry          every module-level jitted kernel is registered

A kernel opts out of a checker via its spec's `suppress` set, or — for
the registry-completeness checker — a `# gubtrace: ok[=registry]`
pragma on the module-level `foo = jax.jit(...)` assignment line.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

_PRAGMA_RE = re.compile(r"#\s*gubtrace:\s*ok(?:=(?P<names>[\w,\-]+))?")


@dataclass(frozen=True)
class Finding:
    checker: str
    kernel: str  # registered kernel name ("-" for cross-kernel findings)
    message: str
    severity: str = "error"  # "error" | "warning"
    where: str = ""  # source location hint (file:line when known)

    def render(self) -> str:
        loc = f" ({self.where})" if self.where else ""
        return (
            f"{self.kernel}: [{self.checker}] {self.severity}: "
            f"{self.message}{loc}"
        )


@dataclass
class BuiltKernel:
    """A kernel instantiated over its canonical signature matrix.

    `fn` is the *jitted* entrypoint (donation/recompile probe it);
    `trace_fn` is what make_jaxpr traces (usually the un-jitted impl).
    `signatures` maps signature name -> a zero-arg builder returning a
    fresh concrete args tuple — a builder, not a tuple, because the
    recompile audit executes kernels whose donated buffers die on
    first use.  Every built tuple must be safe to execute on CPU at
    the canonical shapes.
    """

    fn: Callable
    trace_fn: Callable
    signatures: Dict[str, Callable[[], tuple]]
    # Pytree-path substrings marking int64 counter/timestamp inputs
    # whose dataflow the dtype checker taints (matched against the
    # flattened keypath string, e.g. "[0].remaining" or "[2]").
    counters: Tuple[str, ...] = ()
    # Declared tainted-cast budget: {"to_f64": n, "to_f32": n,
    # "to_i32": n, ...}.  Any tainted convert_element_type beyond the
    # declared multiset is an error (see checkers/dtype.py).
    allowed_casts: Dict[str, int] = field(default_factory=dict)
    # Recompile audit: perturbed variants (name -> zero-arg args
    # builder, e.g. python-scalar `now`) and the declared total
    # jit-cache-entry budget after replaying every signature twice +
    # every variant.
    perturbations: Dict[str, Callable[[], tuple]] = field(
        default_factory=dict
    )
    recompile_budget: Optional[int] = None
    # Donation: expected aliased input leaves (None = every donated
    # leaf must alias; 0 = kernel declares no donation).
    expect_aliased: Optional[int] = None


@dataclass
class KernelSpec:
    name: str
    where: str  # repo-relative source module of the kernel
    build: Callable[[], BuiltKernel]
    invariants: frozenset = frozenset(
        {"dtype-taint", "host-escape", "donation", "primitive-budget",
         "recompile"}
    )
    suppress: frozenset = frozenset()

    def checks(self) -> frozenset:
        return self.invariants - self.suppress


# -- jaxpr walking --------------------------------------------------------

def subjaxprs(eqn) -> List[Any]:
    """Every sub-jaxpr (closed or open) of one equation, any primitive."""
    out: List[Any] = []
    for v in eqn.params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for x in vals:
            if hasattr(x, "eqns"):  # open Jaxpr
                out.append(x)
            elif hasattr(x, "jaxpr") and getattr(x, "jaxpr", None) is not None:
                out.append(x.jaxpr)  # ClosedJaxpr
    return out


def iter_eqns(jaxpr) -> Iterable[Any]:
    """All equations of a (possibly closed) jaxpr, recursively."""
    j = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    for eqn in j.eqns:
        yield eqn
        for sub in subjaxprs(eqn):
            yield from iter_eqns(sub)


def eqn_source(eqn) -> str:
    """Best-effort user file:line for an equation (repo frames first)."""
    try:
        frames = list(eqn.source_info.traceback.frames)
    except Exception:
        return ""
    best = ""
    for fr in frames:
        fname = getattr(fr, "file_name", "")
        line = getattr(fr, "line_num", 0) or getattr(fr, "start_line", 0)
        if "gubernator_tpu" in fname or "gubtrace_fixtures" in fname:
            return f"{fname.rsplit('/repo/', 1)[-1]}:{line}"
        if not best and "site-packages" not in fname:
            best = f"{fname}:{line}"
    return best


def taint_mask(args: tuple, counters: Sequence[str]) -> List[bool]:
    """Per-flattened-leaf taint mask for `args`, aligned with the invars
    of make_jaxpr over the same args (both use tree_flatten order)."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(args)
    mask = []
    for path, _leaf in flat:
        key = jax.tree_util.keystr(path)
        mask.append(any(pat in key for pat in counters))
    return mask


class Checker:
    """Base jaxpr checker: `check` runs per kernel."""

    name = "base"

    def check(self, spec: KernelSpec, built: BuiltKernel,
              ctx: "RunContext") -> Iterable[Finding]:
        return ()

    def finalize(self, ctx: "RunContext") -> Iterable[Finding]:
        return ()


@dataclass
class RunContext:
    """Shared state for one gubtrace run."""

    root: Any  # Path to the repo root
    golden_dir: Any  # Path to the golden snapshot dir
    update_golden: bool = False
    # kernel name -> {sig name -> closed jaxpr} (filled by the runner,
    # consumed by checkers and the CLI's failure dumps)
    jaxprs: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    # names of kernels that failed to build (skipped with a warning)
    skipped: List[str] = field(default_factory=list)


def trace_kernel(built: BuiltKernel) -> Dict[str, Any]:
    """make_jaxpr over every canonical signature."""
    import jax

    out = {}
    for sig_name, make_args in built.signatures.items():
        out[sig_name] = jax.make_jaxpr(built.trace_fn)(*make_args())
    return out


def run_kernels(
    specs: Sequence[KernelSpec],
    checkers: Sequence[Checker],
    ctx: RunContext,
) -> List[Finding]:
    findings: List[Finding] = []
    for spec in specs:
        try:
            built = spec.build()
            ctx.jaxprs[spec.name] = trace_kernel(built)
        except Exception as e:  # environment gap (e.g. missing dep)
            ctx.skipped.append(spec.name)
            findings.append(Finding(
                checker="trace", kernel=spec.name, severity="error",
                message=f"failed to build/trace: {type(e).__name__}: {e}",
            ))
            continue
        enabled = spec.checks()
        for ch in checkers:
            if ch.name not in enabled:
                continue
            try:
                findings.extend(ch.check(spec, built, ctx))
            except Exception as e:  # one kernel's quirk, not the run's
                findings.append(Finding(
                    checker=ch.name, kernel=spec.name,
                    message=f"checker crashed: {type(e).__name__}: {e}",
                ))
    for ch in checkers:
        findings.extend(ch.finalize(ctx))
    findings.sort(key=lambda f: (f.kernel, f.checker, f.message))
    return findings
