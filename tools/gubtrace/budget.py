"""primitive-budget: golden per-kernel counts of expensive primitives.

The hot-path kernels earn their throughput by a known, reviewed set of
expensive XLA ops — apply_batch is "bucket gather → claim sort →
lane arithmetic → scatter" and nothing else.  A refactor that quietly
adds one more `gather` (a stray fancy-index), a `sort`, or an extra
collective doubles a measured cost without any test failing.  Each
registered kernel's counts of the budgeted primitives are snapshotted
under tools/gubtrace/golden/<kernel>.json; a drift fails CI with a
diff, and an intentional change is re-snapshotted with
`python -m tools.gubtrace --update`.

Counts are static (loop bodies count once, not per iteration) and
recurse through every sub-jaxpr.
"""
from __future__ import annotations

import json
from collections import Counter
from typing import Dict, Iterable, List

from tools.gubtrace.core import (
    BuiltKernel,
    Checker,
    Finding,
    KernelSpec,
    RunContext,
    iter_eqns,
)

# The expensive-primitive watchlist: memory-bound data movement
# (gather/scatter), O(n log n) work (sort), control flow that defeats
# fusion (while/scan/cond), and inter-chip collectives.
BUDGETED = (
    "gather",
    "scatter",
    "scatter-add",
    "sort",
    "while",
    "scan",
    "cond",
    "all_to_all",
    "all_gather",
    "psum",
    "pallas_call",
)


def count_budgeted(jaxpr) -> Dict[str, int]:
    c: Counter = Counter()
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in BUDGETED:
            c[name] += 1
    return {k: c[k] for k in sorted(c)}


class PrimitiveBudgetChecker(Checker):
    name = "primitive-budget"

    def check(self, spec: KernelSpec, built: BuiltKernel,
              ctx: RunContext) -> Iterable[Finding]:
        observed = {
            sig: count_budgeted(jaxpr)
            for sig, jaxpr in ctx.jaxprs[spec.name].items()
        }
        path = ctx.golden_dir / f"{spec.name}.json"
        if ctx.update_golden:
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text(
                json.dumps({"primitives": observed}, indent=2,
                           sort_keys=True) + "\n",
                encoding="utf-8",
            )
            return ()
        if not path.is_file():
            return [Finding(
                checker=self.name, kernel=spec.name,
                message=(
                    "no golden snapshot; run "
                    "`python -m tools.gubtrace --update` and commit "
                    f"{path.name}"
                ),
            )]
        golden = json.loads(path.read_text(encoding="utf-8"))["primitives"]
        out: List[Finding] = []
        for sig in sorted(set(golden) | set(observed)):
            g, o = golden.get(sig), observed.get(sig)
            if g == o:
                continue
            if g is None or o is None:
                out.append(Finding(
                    checker=self.name, kernel=spec.name,
                    message=(
                        f"signature matrix drifted: '{sig}' "
                        f"{'added' if g is None else 'removed'} — "
                        "re-snapshot with --update"
                    ),
                ))
                continue
            diffs = [
                f"{p}: golden {g.get(p, 0)} -> observed {o.get(p, 0)}"
                for p in sorted(set(g) | set(o))
                if g.get(p, 0) != o.get(p, 0)
            ]
            out.append(Finding(
                checker=self.name, kernel=spec.name,
                message=(
                    f"[{sig}] expensive-primitive counts drifted "
                    "(intentional? re-snapshot with --update): "
                    + "; ".join(diffs)
                ),
            ))
        return out
