"""donation: every declared donate_argnums buffer survives to the HLO.

`donate_argnums` is how the 10M-key state table avoids being copied on
every step — a dropped donation silently doubles the table's HBM
traffic and footprint.  XLA *warns* (once, easily lost in logs) and
carries on.  This checker fails instead: it lowers each kernel at its
first canonical signature and requires that the number of aliased
input buffers matches the number of donated leaves.

Two lowering shapes exist:
  * single-device jits record aliasing as per-parameter
    `tf.aliasing_output` attrs in the StableHLO;
  * SPMD (shard_map) lowerings only materialize aliasing at compile
    time, as the compiled module's `input_output_alias={...}` table —
    so when the StableHLO shows none we compile (CPU, small shapes)
    and parse that.
"""
from __future__ import annotations

import re
from typing import Iterable, List

from tools.gubtrace.core import (
    BuiltKernel,
    Checker,
    Finding,
    KernelSpec,
    RunContext,
)

# One `{out_idx}: (param, {shape_idx}, may-alias)` entry per aliased
# buffer in the compiled module's input_output_alias table.
_ALIAS_ENTRY_RE = re.compile(r"\{[\d,\s]*\}:\s*\(\d+,[^)]*-alias\)")


def _compiled_alias_count(compiled_text: str) -> int:
    if "input_output_alias=" not in compiled_text:
        return 0
    return len(_ALIAS_ENTRY_RE.findall(compiled_text))


class DonationChecker(Checker):
    name = "donation"

    def check(self, spec: KernelSpec, built: BuiltKernel,
              ctx: RunContext) -> Iterable[Finding]:
        import jax

        sig_name, make_args = next(iter(built.signatures.items()))
        try:
            lowered = built.fn.lower(*make_args())
        except Exception as e:
            return [Finding(
                checker=self.name, kernel=spec.name, severity="warning",
                message=f"could not lower for donation check: {e}",
            )]
        donated = sum(
            1 for a in jax.tree_util.tree_leaves(lowered.args_info)
            if a.donated
        )
        expected = built.expect_aliased
        if expected is None:
            expected = donated
        out: List[Finding] = []
        if donated == 0 and expected:
            return [Finding(
                checker=self.name, kernel=spec.name,
                message=(
                    f"[{sig_name}] expected {expected} donated leaves "
                    "but the lowering donates none — donate_argnums "
                    "was dropped"
                ),
            )]
        if expected == 0:
            return ()
        aliased = lowered.as_text().count("tf.aliasing_output")
        if aliased < expected:
            # SPMD lowerings record aliasing only post-compile.
            try:
                aliased = _compiled_alias_count(
                    lowered.compile().as_text()
                )
            except Exception as e:
                return [Finding(
                    checker=self.name, kernel=spec.name,
                    severity="warning",
                    message=(
                        f"could not compile for donation check: {e}"
                    ),
                )]
        if aliased < expected:
            out.append(Finding(
                checker=self.name, kernel=spec.name,
                message=(
                    f"[{sig_name}] {donated} input leaves are donated "
                    f"but only {aliased}/{expected} alias an output in "
                    "the lowered computation — the donation is "
                    "silently dropped (double HBM traffic on this "
                    "buffer)"
                ),
            ))
        return out
