"""gubtrace: jaxpr-level static verification of every jitted kernel.

gubguard (tools/gubguard) polices the Python source; gubtrace polices
the *traced computation* — the jaxprs XLA actually compiles — where
the hot-path invariants hold or break.  Every registered kernel
(tools/gubtrace/registry.py) is traced over a canonical shape/dtype
matrix on CPU and checked for:

  dtype-taint       counter/timestamp int64 dataflow never silently
                    narrows or floats beyond the declared budget
  host-escape       no callback primitives compiled into a kernel
  donation          declared donate_argnums survive into the lowering
  primitive-budget  expensive-primitive counts match the golden
                    snapshots (tools/gubtrace/golden/)
  recompile         jit cache misses match the declared budget
  registry          every module-level jitted kernel is registered

Run:

    JAX_PLATFORMS=cpu python -m tools.gubtrace           # verify
    python -m tools.gubtrace --update                    # re-snapshot

Exit status 0 = clean (warnings allowed), 1 = errors.  The runtime
counterpart is `gubernator-tpu-microbench --recompile-audit`.
"""
from __future__ import annotations

from pathlib import Path
from typing import List, Optional, Sequence

from tools.gubtrace.budget import PrimitiveBudgetChecker
from tools.gubtrace.completeness import RegistryCompletenessChecker
from tools.gubtrace.core import (
    Checker,
    Finding,
    KernelSpec,
    RunContext,
    run_kernels,
)
from tools.gubtrace.donation import DonationChecker
from tools.gubtrace.dtype import DtypeTaintChecker
from tools.gubtrace.hostescape import HostEscapeChecker
from tools.gubtrace.recompile import RecompileChecker

ALL_CHECKERS = (
    "dtype-taint",
    "host-escape",
    "donation",
    "primitive-budget",
    "recompile",
    "registry",
)

GOLDEN_DIR = Path(__file__).parent / "golden"


def make_checkers(
    select: Optional[Sequence[str]] = None,
    registered: Optional[Sequence[str]] = None,
) -> List[Checker]:
    factory = {
        "dtype-taint": DtypeTaintChecker,
        "host-escape": HostEscapeChecker,
        "donation": DonationChecker,
        "primitive-budget": PrimitiveBudgetChecker,
        "recompile": RecompileChecker,
        "registry": lambda: RegistryCompletenessChecker(registered or ()),
    }
    names = list(select) if select else list(ALL_CHECKERS)
    unknown = [n for n in names if n not in factory]
    if unknown:
        raise ValueError(f"unknown checkers: {unknown}")
    return [factory[n]() for n in names]


def run(
    select: Optional[Sequence[str]] = None,
    kernels: Optional[Sequence[str]] = None,
    root: Optional[Path] = None,
    golden_dir: Optional[Path] = None,
    update_golden: bool = False,
    specs: Optional[Sequence[KernelSpec]] = None,
    ctx_out: Optional[list] = None,
) -> List[Finding]:
    """Run the selected checkers over the registry; returns findings.

    `specs` overrides the registry (the seeded-violation fixtures use
    this); `ctx_out`, when given, receives the RunContext (the CLI
    dumps failing kernels' jaxprs from it).
    """
    from tools.gubtrace import registry as reg

    all_specs = list(specs) if specs is not None else reg.specs()
    if kernels:
        unknown = set(kernels) - {s.name for s in all_specs}
        if unknown:
            raise ValueError(f"unknown kernels: {sorted(unknown)}")
        all_specs = [s for s in all_specs if s.name in kernels]
    ctx = RunContext(
        root=root or Path.cwd(),
        golden_dir=golden_dir or GOLDEN_DIR,
        update_golden=update_golden,
    )
    if ctx_out is not None:
        ctx_out.append(ctx)
    checkers = make_checkers(
        select, registered=[s.name for s in all_specs]
    )
    return run_kernels(all_specs, checkers, ctx)
