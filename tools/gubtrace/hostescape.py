"""host-escape: no callback primitives inside hot-path kernels.

A `pure_callback` / `io_callback` / `debug_callback` inside a jitted
kernel inserts a device→host round-trip into the compiled computation —
through the TPU tunnel that is 70–300 ms per transition
(docs/invariants.md §1), which single-handedly blows the 2 ms p99
budget.  gubguard's host-sync checker polices Python *call sites*; this
one polices the *traced computation*, where a callback smuggled in via
a library helper (e.g. `jax.debug.print` left in a kernel) still shows
up as a primitive.
"""
from __future__ import annotations

from typing import Iterable, List

from tools.gubtrace.core import (
    BuiltKernel,
    Checker,
    Finding,
    KernelSpec,
    RunContext,
    eqn_source,
    iter_eqns,
)

# Primitive names that imply a host transition inside the computation.
FORBIDDEN = frozenset({
    "pure_callback",
    "io_callback",
    "debug_callback",
    "host_callback_call",
    "outside_call",
    "infeed",
    "outfeed",
})


class HostEscapeChecker(Checker):
    name = "host-escape"

    def check(self, spec: KernelSpec, built: BuiltKernel,
              ctx: RunContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for sig_name, jaxpr in ctx.jaxprs[spec.name].items():
            for eqn in iter_eqns(jaxpr):
                name = eqn.primitive.name
                if name in FORBIDDEN or name.endswith("_callback"):
                    out.append(Finding(
                        checker=self.name, kernel=spec.name,
                        message=(
                            f"[{sig_name}] host-transition primitive "
                            f"'{name}' compiled into the kernel"
                        ),
                        where=eqn_source(eqn),
                    ))
            break  # structure is signature-invariant
        return out
