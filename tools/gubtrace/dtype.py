"""dtype-taint: no silent counter/timestamp dtype escapes.

The failure class ("When Two is Worse Than One", PAPERS.md — silent
accounting divergence): a refactor introduces an int64→float or
int64→int32 `convert_element_type` on counter dataflow, XLA compiles it
without complaint, and remaining/expiry arithmetic silently loses
precision (f32 is exact only to 2^24; i32 wraps at 2^31 — both far
below real token budgets and unix-ms timestamps).

Mechanics: each kernel declares its int64 counter/timestamp inputs
(`BuiltKernel.counters`, pytree-path patterns).  Taint starts on those
invars and propagates through every equation along the int64/uint64
lineage — once a value is *deliberately* converted (the leaky bucket's
Go-float arithmetic), the cast is charged against the kernel's declared
`allowed_casts` budget and the float lineage is not re-flagged.  Any
tainted cast beyond the declared multiset is an error naming the
offending source line.

Casts are bucketed by destination:
  to_f64  — deliberate Go-semantics float math (budgeted per kernel)
  to_f32 / to_f16 — precision loss for counters (budget only when the
            kernel's contract bounds the value, e.g. CMS cells)
  to_i32 / narrower — wrap/truncation (budget only for fields whose
            contract bounds them, e.g. packed algo enums)
Casts to bool (lane predicates) and within the 64-bit integer family
are free — they cannot corrupt a counter.  Also free: *index* casts,
i64→i32 whose every (transitive, through shape-only ops) consumer is
the index operand of a gather/scatter/dynamic-slice — jnp indexing
narrows indices to i32 as a matter of course and slot/bucket spaces
are bounded by table geometry (num_slots << 2^31), not by counter
values.
"""
from __future__ import annotations

from collections import Counter
from typing import Dict, Iterable, List, Set, Tuple

from tools.gubtrace.core import (
    BuiltKernel,
    Checker,
    Finding,
    KernelSpec,
    RunContext,
    eqn_source,
    taint_mask,
)

_WIDE_INT = ("int64", "uint64")


def _bucket(dtype_name: str) -> str:
    if dtype_name.startswith("float64"):
        return "to_f64"
    if dtype_name.startswith(("float32",)):
        return "to_f32"
    if dtype_name.startswith(("float16", "bfloat16")):
        return "to_f16"
    if dtype_name.startswith(("int32", "uint32")):
        return "to_i32"
    if dtype_name.startswith(("int16", "uint16", "int8", "uint8")):
        return "to_i8"
    return ""


# Ops that only reshape/relocate an index lineage without using values.
# pbroadcast qualifies: shard_map inserts it to replicate a P()-specced
# value across the mesh axis (e.g. a replicated fingerprint grid whose
# derived bucket indices feed a gather over the sharded table) — it
# moves the lineage between devices without consuming it.
_SHAPE_ONLY = frozenset({
    "broadcast_in_dim", "reshape", "concatenate", "slice", "squeeze",
    "expand_dims", "transpose", "rev", "copy", "pbroadcast",
})


def _index_positions(eqn) -> List[int]:
    """invars positions that are *index* operands of this primitive."""
    name = eqn.primitive.name
    n = len(eqn.invars)
    if name == "gather":
        return [1]
    if name in ("scatter", "scatter-add", "scatter-mul", "scatter-min",
                "scatter-max"):
        return [1]
    if name == "dynamic_slice":
        return list(range(1, n))
    if name == "dynamic_update_slice":
        return list(range(2, n))
    return []


def _is_index_only(var, eqn_of_var, consumers, outvar_ids) -> bool:
    """True when every transitive consumer (through shape-only ops) of
    `var` uses it as a gather/scatter/dynamic-slice index."""
    seen = set()
    stack = [var]
    while stack:
        v = stack.pop()
        if id(v) in seen:
            continue
        seen.add(id(v))
        if id(v) in outvar_ids:
            return False  # escapes this jaxpr — can't prove index-only
        uses = consumers.get(id(v))
        if not uses:
            return False  # dead or untracked — be conservative
        for eqn in uses:
            idx_pos = set(_index_positions(eqn))
            positions = [
                i for i, iv in enumerate(eqn.invars) if iv is v
            ]
            if all(p in idx_pos for p in positions):
                continue
            if eqn.primitive.name in _SHAPE_ONLY:
                stack.extend(eqn.outvars)
                continue
            return False
    return True


class _Walk:
    """One taint-propagation walk over a closed jaxpr."""

    def __init__(self) -> None:
        self.casts: Counter = Counter()
        self.sites: Dict[str, List[str]] = {}

    def _tainted_outs(self, eqn, tin: List[bool]) -> List[bool]:
        """Default propagation: any tainted input taints every wide-int
        output (float/bool/narrow outputs are only reached via an
        explicit convert, which is handled separately)."""
        if not any(tin):
            return [False] * len(eqn.outvars)
        return [
            str(v.aval.dtype) in _WIDE_INT for v in eqn.outvars
        ]

    def walk(self, jaxpr, taint_in: List[bool]) -> List[bool]:
        """Returns the taint mask of jaxpr.outvars."""
        j = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
        tainted: Set[int] = set()
        consumers: Dict[int, list] = {}
        for eqn in j.eqns:
            for v in eqn.invars:
                if not hasattr(v, "val"):
                    consumers.setdefault(id(v), []).append(eqn)
        outvar_ids = {id(v) for v in j.outvars}

        def is_t(v) -> bool:
            return not hasattr(v, "val") and id(v) in tainted

        for var, t in zip(j.invars, taint_in):
            if t:
                tainted.add(id(var))

        for eqn in j.eqns:
            tin = [is_t(v) for v in eqn.invars]
            name = eqn.primitive.name
            if name == "convert_element_type" and tin[0]:
                src = str(eqn.invars[0].aval.dtype)
                dst = str(eqn.outvars[0].aval.dtype)
                if src in _WIDE_INT:
                    b = _bucket(dst)
                    if b in ("to_i32", "to_i8") and _is_index_only(
                        eqn.outvars[0], eqn, consumers, outvar_ids
                    ):
                        continue  # index lineage — bounded by geometry
                    if b:
                        self.casts[b] += 1
                        self.sites.setdefault(b, []).append(
                            f"{src}->{dst} at {eqn_source(eqn) or '?'}"
                        )
                        continue  # converted lineage is not re-tainted
                # wide-int <-> wide-int keeps taint
                if dst in _WIDE_INT:
                    tainted.add(id(eqn.outvars[0]))
                continue
            tout = self._descend(eqn, tin)
            for v, t in zip(eqn.outvars, tout):
                if t:
                    tainted.add(id(v))
        return [is_t(v) for v in j.outvars]

    def _descend(self, eqn, tin: List[bool]) -> List[bool]:
        name = eqn.primitive.name
        p = eqn.params
        if name == "pjit" or (
            "jaxpr" in p and name in ("closed_call", "shard_map")
        ):
            return self.walk(p["jaxpr"], tin)
        if name in ("custom_jvp_call", "custom_vjp_call") and \
                p.get("call_jaxpr") is not None:
            return self.walk(p["call_jaxpr"], tin)
        if name == "scan":
            return self._fixpoint(
                p["jaxpr"], tin, n_carry=p["num_carry"],
                carry_lo=p["num_consts"],
            )
        if name == "while":
            nc, nb = p["cond_nconsts"], p["body_nconsts"]
            carry_in = tin[nc + nb:]
            body_tin = tin[nc:nc + nb] + carry_in
            out = self._fixpoint(
                p["body_jaxpr"], body_tin, n_carry=len(carry_in),
                carry_lo=nb,
            )
            return out
        if name == "cond":
            outs = None
            for br in p["branches"]:
                o = self.walk(br, tin[1:])
                outs = o if outs is None else [
                    a or b for a, b in zip(outs, o)
                ]
            return outs or [False] * len(eqn.outvars)
        if name == "pallas_call":
            # Opaque: the Pallas kernel body is differentially tested
            # bit-exact against its XLA reference; taint stops here.
            return [False] * len(eqn.outvars)
        return self._tainted_outs(eqn, tin)

    def _fixpoint(self, jaxpr, tin: List[bool], n_carry: int,
                  carry_lo: int) -> List[bool]:
        """Loop bodies: iterate until the carried taint stabilizes."""
        tin = list(tin)
        for _ in range(8):
            out = self.walk(jaxpr, tin)
            carry_out = out[:n_carry]
            cur = tin[carry_lo:carry_lo + n_carry]
            nxt = [a or b for a, b in zip(cur, carry_out)]
            if nxt == cur:
                return out
            tin[carry_lo:carry_lo + n_carry] = nxt
        return out


class DtypeTaintChecker(Checker):
    name = "dtype-taint"

    def check(self, spec: KernelSpec, built: BuiltKernel,
              ctx: RunContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for sig_name, make_args in built.signatures.items():
            mask = taint_mask(make_args(), built.counters)
            walk = _Walk()
            walk.walk(ctx.jaxprs[spec.name][sig_name], mask)
            allowed = Counter(built.allowed_casts)
            for bucket, n in sorted(walk.casts.items()):
                lim = allowed.get(bucket, 0)
                if n > lim:
                    extra = walk.sites[bucket][lim:]
                    out.append(Finding(
                        checker=self.name, kernel=spec.name,
                        message=(
                            f"[{sig_name}] {n} tainted {bucket} cast(s) "
                            f"on int64 counter dataflow, budget {lim}; "
                            "undeclared: " + "; ".join(extra[:4])
                        ),
                    ))
            for bucket, lim in sorted(allowed.items()):
                if walk.casts.get(bucket, 0) < lim:
                    out.append(Finding(
                        checker=self.name, kernel=spec.name,
                        severity="warning",
                        message=(
                            f"[{sig_name}] declared {bucket} budget "
                            f"{lim} but observed "
                            f"{walk.casts.get(bucket, 0)} — shrink the "
                            "declaration (stale budget hides the next "
                            "regression)"
                        ),
                    ))
            break  # taint structure is signature-invariant; one is enough
        return out
