"""registry: every module-level jitted kernel must be registered.

The registry is only a gate if it is complete — a new
`foo = jax.jit(...)` added to a kernel module without a registry entry
would silently skip every gubtrace invariant.  This checker AST-scans
the watched kernel modules for module-level `jax.jit(...)` assignments
and requires each bound name to appear in the registry (factory-built
kernels — the shard_map steps — are registered by hand and listed in
FACTORY_KERNELS for the same reason).

A deliberate exemption takes a `# gubtrace: ok[=registry]` pragma on
the assignment line.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Set

from tools.gubtrace.core import _PRAGMA_RE, Checker, Finding, RunContext

# Modules whose module-level jits the registry must cover.  The mesh
# entrypoints (parallel/sharded.py, parallel/global_sync.py) are
# factory-built shard_map kernels — no module-level jits today — but
# watching them means a future `X = jax.jit(...)` there is flagged
# instead of silently shipping unverified.
WATCHED_MODULES = (
    "gubernator_tpu/ops/step.py",
    "gubernator_tpu/ops/sketch.py",
    "gubernator_tpu/ops/pallas/cms_kernel.py",
    "gubernator_tpu/ops/ring.py",
    "gubernator_tpu/parallel/sharded.py",
    "gubernator_tpu/parallel/global_sync.py",
)


def _is_jax_jit_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    return (
        isinstance(f, ast.Attribute) and f.attr == "jit"
        and isinstance(f.value, ast.Name) and f.value.id == "jax"
    )


def module_level_jits(source: str) -> List[tuple]:
    """(name, line) for every module-level `X = jax.jit(...)`."""
    tree = ast.parse(source)
    out = []
    for node in tree.body:
        if isinstance(node, ast.Assign) and _is_jax_jit_call(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.append((t.id, node.lineno))
    return out


def _pragma_lines(source: str, checker: str) -> Set[int]:
    lines = set()
    for i, line in enumerate(source.splitlines(), start=1):
        m = _PRAGMA_RE.search(line)
        if not m:
            continue
        names = m.group("names")
        if names is None or checker in names.split(","):
            lines.add(i)
    return lines


class RegistryCompletenessChecker(Checker):
    name = "registry"

    def __init__(self, registered: Iterable[str],
                 watched: Iterable[str] = WATCHED_MODULES) -> None:
        self.registered = set(registered)
        self.watched = tuple(watched)

    def finalize(self, ctx: RunContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for rel in self.watched:
            path = ctx.root / rel
            if not path.is_file():
                out.append(Finding(
                    checker=self.name, kernel="-", severity="warning",
                    message=f"watched kernel module missing: {rel}",
                ))
                continue
            source = path.read_text(encoding="utf-8")
            ok_lines = _pragma_lines(source, self.name)
            for name, line in module_level_jits(source):
                if name in self.registered or line in ok_lines:
                    continue
                out.append(Finding(
                    checker=self.name, kernel=name,
                    message=(
                        f"jitted entrypoint '{name}' ({rel}:{line}) is "
                        "not in the gubtrace registry — it ships with "
                        "ZERO device-side invariant coverage; register "
                        "it in tools/gubtrace/registry.py or pragma "
                        "the assignment"
                    ),
                    where=f"{rel}:{line}",
                ))
        return out
