"""Concrete witnesses: execute the real kernel at the envelope corner.

When the interval analysis reports that an intermediate can leave its
dtype range, the failure report should not be an abstract claim — this
module synthesizes the minimal concrete input at the violated bound's
interval corner (every envelope-matched integer leaf at its declared
max), executes the REAL kernel eagerly on CPU, and reports the output
extremes so the wrap is visible in black and white.  The shipped
negative-control fixture (tools/gubrange/fixture.py) keeps this honest:
its witness demonstrably wraps negative from all-nonnegative inputs.
"""
from __future__ import annotations

from typing import Optional

from tools.gubrange.envelope import Envelope, corner_args


def run_witness(
    built, env: Envelope, sig_name: str, corner: str = "max"
) -> Optional[str]:
    """Execute trace_fn at the envelope corner; returns a one-line
    report of the output extremes (None if execution itself fails)."""
    import jax
    import numpy as np

    make_args = built.signatures[sig_name]
    try:
        args = corner_args(env, make_args(), corner=corner)
        with jax.disable_jit():
            out = built.trace_fn(*args)
    except Exception as e:
        return f"witness execution failed: {type(e).__name__}: {e}"

    flat, _ = jax.tree_util.tree_flatten_with_path((out,))
    parts = []
    wrapped = False
    seeded_nonneg = all(r.min >= 0 for r in env.inputs)
    for path, leaf in flat:
        arr = np.asarray(leaf)
        if arr.size == 0 or arr.dtype.kind not in "iu":
            continue
        lo, hi = int(arr.min()), int(arr.max())
        key = jax.tree_util.keystr(path)
        parts.append(f"{key}∈[{lo}, {hi}]")
        if seeded_nonneg and lo < 0:
            wrapped = True
    head = (
        "WRAPPED (negative output from all-nonnegative inputs): "
        if wrapped else ""
    )
    return (
        f"{head}executed at envelope {corner}-corner "
        f"(sig {sig_name}): " + "; ".join(parts)
    )
