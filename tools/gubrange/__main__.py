"""CLI: python -m tools.gubrange [--select ranges,suffix] [--kernel N].

Must configure the platform BEFORE jax initializes: the analyzer runs
device-free (JAX_PLATFORMS=cpu) on a virtual 8-device host platform so
the mesh kernels trace exactly as CI's virtual pod slice does.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _pin_cpu_platform() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def main(argv=None) -> int:
    _pin_cpu_platform()
    from pathlib import Path

    from tools.gubrange import ALL_PHASES, run

    ap = argparse.ArgumentParser(
        prog="gubrange",
        description=(
            "Interval abstract interpretation + time-unit taint over "
            "every registered kernel (see docs/gubrange.md)."
        ),
    )
    ap.add_argument(
        "--select", metavar="PHASES",
        help="comma-separated phase subset of: " + ", ".join(ALL_PHASES),
    )
    ap.add_argument(
        "--kernel", action="append", metavar="NAME",
        help="restrict the ranges phase to this kernel (repeatable)",
    )
    ap.add_argument(
        "--update", action="store_true",
        help="rewrite each envelope's expect_peak to the proved peak",
    )
    ap.add_argument(
        "--list", action="store_true", dest="list_kernels",
        help="list registered kernels and their envelopes, then exit",
    )
    ap.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings as a JSON array",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="treat warnings as errors (also honors GUBRANGE_STRICT)",
    )
    ap.add_argument(
        "--root", default=".",
        help="repo root (default: cwd)",
    )
    ap.add_argument(
        "--dump-dir", default=None,
        help=(
            "where to write failing kernels' analysis dumps "
            "(default: $GUBRANGE_DUMP_DIR or gubrange-dumps)"
        ),
    )
    args = ap.parse_args(argv)

    if args.list_kernels:
        from tools.gubrange.envelope import load_envelopes
        from tools.gubtrace.registry import specs

        envelopes = load_envelopes()
        for s in specs():
            env = envelopes.get(s.name)
            tag = env.path.name if env and env.path else "MISSING"
            print(f"{s.name}  ({s.where})  envelope={tag}")
        return 0

    select = (
        [s.strip() for s in args.select.split(",") if s.strip()]
        if args.select else None
    )
    from gubernator_tpu.core.config import (
        gubrange_dump_dir_from_env,
        gubrange_strict_from_env,
    )

    strict = args.strict or gubrange_strict_from_env()
    dump_dir = Path(args.dump_dir or gubrange_dump_dir_from_env())
    try:
        findings = run(
            select=select,
            kernel=",".join(args.kernel) if args.kernel else None,
            root=Path(args.root),
            update=args.update,
            dump_dir=dump_dir,
        )
    except ValueError as e:
        print(f"gubrange: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
    errors = [
        f for f in findings
        if f.severity == "error" or (strict and f.severity == "warning")
    ]
    warnings = [f for f in findings if f.severity == "warning"]
    if not args.as_json:
        print(
            f"gubrange: {len(errors)} error(s), "
            f"{len(warnings)} warning(s)"
        )
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
