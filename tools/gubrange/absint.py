"""The interval + unit abstract interpreter over closed jaxprs.

One walk carries BOTH abstractions — an exact interval (unbounded
Python ints / IEEE floats) and a dimensional unit tag — through every
equation of a kernel's jaxpr, recursing into pjit/scan/while/cond the
same way the gubtrace dtype-taint walk does (tools/gubtrace/dtype.py).

Finding classes (see docs/gubrange.md):

  overflow           signed-int arithmetic whose exact result interval
                     leaves the output dtype range — NEVER budgetable;
                     this is the theorem the plane proves
  unbounded-arith    signed-int arithmetic on a TOP (envelope-unseeded)
                     operand — budgetable with a written reason
  int-div-zero       integer div/rem by a zero-inclusive interval
  float-div-zero     float division by a zero-inclusive interval (the
                     idiomatic `where(x != 0, a / x, 0)` guard is
                     invisible to a non-relational domain — budgeted)
  negative-duration  a possibly-negative interval added to an absolute
                     timestamp (e.g. a Gregorian expiry already in the
                     past) — budgeted where the reference behaves so
  unit-mismatch      dimensional-algebra violation (ns+ms, epoch+epoch,
                     hits×duration, …)
  unknown-primitive  a primitive with no transfer function — the walk
                     goes conservative (TOP), and says so

The walk also tracks `peak`: the largest absolute bound any signed-int
arithmetic intermediate can reach.  The envelope's `expect_peak` must
EQUAL it (exactness cuts both ways, like gubproof's expect_max): an
envelope declaring a looser peak than the analysis proves reachable is
an error, so envelopes cannot rot into theater.

Scan bodies are unrolled exactly (`length` is small for every
registered kernel); while bodies run to a joined fixpoint and widen to
TOP if they fail to stabilize.  Unsigned arithmetic is modular by
definition (sketch row hashing) and never raises findings.  pallas_call
is opaque: outputs are TOP of their dtype (the kernel bodies are
differentially pinned elsewhere).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, replace
from typing import Any, Dict, List, Optional, Sequence

from tools.gubrange import units as U
from tools.gubrange.interval import (
    AbsVal,
    add_bounds,
    div_bounds_float,
    div_bounds_int,
    dtype_kind,
    dtype_range,
    from_rows,
    join_bounds,
    mul_bounds,
    rem_bounds_int,
    sub_bounds,
    top_of,
    trunc_to_int_bounds,
)
from tools.gubtrace.core import eqn_source

# Value-preserving moves: interval and unit pass through untouched
# (the packed-row refinement is dropped — only slice/squeeze/scan,
# handled explicitly, can track which row survives an axis change).
_SHAPE_ONLY = frozenset({
    "broadcast_in_dim", "reshape", "expand_dims", "transpose",
    "rev", "copy", "pbroadcast", "stop_gradient",
    "reduce_precision", "all_gather", "all_to_all", "ppermute", "pvary",
    "device_put", "sharding_constraint", "split",
})

_CMP = frozenset({
    "eq", "ne", "lt", "le", "gt", "ge",
    # total-order variants (XLA lowers unsigned/NaN-aware compares)
    "eq_to", "ne_to", "lt_to", "le_to", "gt_to", "ge_to",
})

_SCAN_UNROLL_CAP = 128
_WHILE_FIXPOINT_CAP = 64


@dataclass(frozen=True)
class Issue:
    cls: str
    message: str
    where: str = ""


def _aval_dtype(v) -> str:
    return str(v.aval.dtype)


def _strip_rows(a: AbsVal) -> AbsVal:
    """Collapse the packed-row refinement to its (already-joined)
    top-level bounds."""
    if a.rows is None:
        return a
    return replace(a, rows=None, rows_axis=0)


def _size(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


class RangeWalk:
    """One interval+unit walk over a closed jaxpr.

    `collective_n` scales psum-style cross-device reductions (the
    registry's canonical mesh is 8 virtual devices).
    """

    def __init__(self, collective_n: int = 8) -> None:
        self.issues: List[Issue] = []
        self.peak: int = 0
        self.collective_n = collective_n
        self._unknown_seen: set = set()
        self._sites_seen: set = set()

    # -- bookkeeping ------------------------------------------------------

    def _report(self, cls: str, eqn, msg: str) -> None:
        where = eqn_source(eqn) or ""
        if where:
            # Budgets license SITES, not dynamic occurrences: an
            # unrolled scan (or a kernel applying the same impl twice)
            # re-walks the same equation and must not multiply the
            # declared count by the trip geometry.
            key = (cls, where)
            if key in self._sites_seen:
                return
            self._sites_seen.add(key)
        self.issues.append(Issue(cls, msg, where))

    def _lit(self, v) -> AbsVal:
        val = v.val
        try:
            import numpy as np

            arr = np.asarray(val)
            if arr.dtype.kind in "iub":
                return AbsVal(int(arr.min()), int(arr.max()))
            return AbsVal(float(arr.min()), float(arr.max()))
        except Exception:
            return top_of(_aval_dtype(v))

    # -- arithmetic result constructors -----------------------------------

    def _mk_arith(self, eqn, out_i: int, lo, hi,
                  unit: Optional[str], ins: Sequence[AbsVal],
                  op: str) -> AbsVal:
        """Bound-check one arithmetic result against its output dtype."""
        dtype = _aval_dtype(eqn.outvars[out_i])
        kind = dtype_kind(dtype)
        rlo, rhi = dtype_range(dtype)
        if kind == "float":
            return AbsVal(float(lo), float(hi), unit=unit)
        if kind == "uint":
            # Modular by definition (hash mixing); wrap widens, no finding.
            if lo < rlo or hi > rhi:
                lo, hi = rlo, rhi
            return AbsVal(lo, hi, unit=unit,
                          top=any(a.top for a in ins))
        # signed int (bool never reaches arith outputs)
        if any(a.top for a in ins):
            self._report(
                "unbounded-arith", eqn,
                f"{op} on an envelope-unseeded {dtype} operand — bound "
                "the input in the kernel envelope or budget this with a "
                "reason",
            )
            return top_of(dtype, unit=unit)
        self.peak = max(self.peak, abs(int(lo)), abs(int(hi)))
        if lo < rlo or hi > rhi:
            self._report(
                "overflow", eqn,
                f"{op}: exact result [{lo}, {hi}] exceeds {dtype} "
                f"[{rlo}, {rhi}] — this CAN wrap at the declared "
                "envelope",
            )
            lo, hi = max(lo, rlo), min(hi, rhi)
        return AbsVal(int(lo), int(hi), unit=unit)

    def _unit2(self, eqn, rule, a: AbsVal, b: AbsVal) -> Optional[str]:
        unit, err = rule(a.unit, b.unit)
        if err:
            self._report("unit-mismatch", eqn, err)
        return unit

    # -- the walk ---------------------------------------------------------

    def walk(self, jaxpr, in_vals: Sequence[AbsVal]) -> List[AbsVal]:
        j = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
        env: Dict[int, AbsVal] = {}

        def read(v) -> AbsVal:
            if hasattr(v, "val"):
                return self._lit(v)
            got = env.get(id(v))
            if got is None:
                return top_of(_aval_dtype(v))
            return got

        consts = getattr(jaxpr, "consts", None)
        if hasattr(j, "constvars"):
            for cv in j.constvars:
                env[id(cv)] = top_of(_aval_dtype(cv))
            if consts is not None:
                import numpy as np

                for cv, cval in zip(j.constvars, consts):
                    try:
                        arr = np.asarray(cval)
                        if arr.dtype.kind in "iub":
                            env[id(cv)] = AbsVal(int(arr.min()),
                                                 int(arr.max()))
                        else:
                            env[id(cv)] = AbsVal(float(arr.min()),
                                                 float(arr.max()))
                    except Exception:
                        pass

        for var, val in zip(j.invars, in_vals):
            env[id(var)] = val

        for eqn in j.eqns:
            ins = [read(v) for v in eqn.invars]
            outs = self._transfer(eqn, ins)
            for v, val in zip(eqn.outvars, outs):
                env[id(v)] = val

        return [read(v) for v in j.outvars]

    # -- per-primitive transfer -------------------------------------------

    def _transfer(self, eqn, ins: List[AbsVal]) -> List[AbsVal]:
        name = eqn.primitive.name
        p = eqn.params

        if name in _SHAPE_ONLY:
            first = _strip_rows(ins[0])
            if name == "split":
                return [first for _ in eqn.outvars]
            return [first]

        if name == "slice":
            a = ins[0]
            if a.rows is not None:
                s = int(p["start_indices"][a.rows_axis])
                l = int(p["limit_indices"][a.rows_axis])
                strides = p.get("strides")
                step = (int(strides[a.rows_axis])
                        if strides is not None else 1)
                picked = a.rows[s:l:step]
                if picked:
                    return [from_rows(picked, a.rows_axis)]
            return [_strip_rows(a)]

        if name == "squeeze":
            a = ins[0]
            if a.rows is not None:
                dims = tuple(int(d) for d in p["dimensions"])
                if a.rows_axis in dims:
                    if len(a.rows) == 1:
                        return [a.rows[0]]
                    return [_strip_rows(a)]
                new_axis = a.rows_axis - sum(
                    1 for d in dims if d < a.rows_axis
                )
                return [replace(a, rows_axis=new_axis)]
            return [a]

        if name in _CMP:
            err = U.compare(ins[0].unit, ins[1].unit)
            if err:
                self._report("unit-mismatch", eqn, err)
            return [AbsVal(0, 1)]

        if name == "add":
            a, b = ins
            self._check_negative_duration(eqn, a, b)
            unit = self._unit2(eqn, U.add, a, b)
            lo, hi = add_bounds(a, b)
            return [self._mk_arith(eqn, 0, lo, hi, unit, ins, "add")]

        if name == "sub":
            a, b = ins
            unit = self._unit2(eqn, U.sub, a, b)
            lo, hi = sub_bounds(a, b)
            return [self._mk_arith(eqn, 0, lo, hi, unit, ins, "sub")]

        if name == "mul":
            a, b = ins
            unit = self._unit2(eqn, U.mul, a, b)
            lo, hi = mul_bounds(a, b)
            return [self._mk_arith(eqn, 0, lo, hi, unit, ins, "mul")]

        if name == "div":
            a, b = ins
            unit = self._unit2(eqn, U.div, a, b)
            if dtype_kind(_aval_dtype(eqn.outvars[0])) == "float":
                lo, hi, zdiv = div_bounds_float(a, b)
                if zdiv:
                    self._report(
                        "float-div-zero", eqn,
                        f"float division by zero-inclusive interval "
                        f"[{b.lo}, {b.hi}]",
                    )
                return [AbsVal(lo, hi, unit=unit)]
            lo, hi, zdiv = div_bounds_int(a, b)
            if zdiv:
                self._report(
                    "int-div-zero", eqn,
                    f"integer division by zero-inclusive interval "
                    f"[{b.lo}, {b.hi}]",
                )
            return [self._mk_arith(eqn, 0, lo, hi, unit, ins, "div")]

        if name == "rem":
            a, b = ins
            lo, hi, zdiv = rem_bounds_int(a, b)
            if zdiv:
                self._report(
                    "int-div-zero", eqn,
                    f"integer remainder by zero-inclusive interval "
                    f"[{b.lo}, {b.hi}]",
                )
            return [self._mk_arith(eqn, 0, lo, hi, ins[0].unit, ins,
                                   "rem")]

        if name == "neg":
            a = ins[0]
            return [self._mk_arith(eqn, 0, -a.hi, -a.lo, a.unit, ins,
                                   "neg")]

        if name == "abs":
            a = ins[0]
            lo = 0 if a.lo < 0 < a.hi or a.lo == 0 or a.hi == 0 else \
                min(abs(a.lo), abs(a.hi))
            hi = max(abs(a.lo), abs(a.hi))
            return [self._mk_arith(eqn, 0, lo, hi, a.unit, ins, "abs")]

        if name == "sign":
            return [AbsVal(-1, 1)]

        if name == "integer_pow":
            a = ins[0]
            y = int(p["y"])
            cands = [a.lo ** y, a.hi ** y]
            if a.lo < 0 < a.hi:
                cands.append(0)
            lo, hi = min(cands), max(cands)
            if y % 2 == 0:
                lo = max(lo, 0)
            return [self._mk_arith(eqn, 0, lo, hi, None, ins,
                                   "integer_pow")]

        if name in ("max", "min"):
            a, b = ins
            unit = self._unit2(eqn, U.join, a, b)
            f = max if name == "max" else min
            return [AbsVal(f(a.lo, b.lo), f(a.hi, b.hi), unit=unit,
                           top=a.top and b.top)]

        if name == "clamp":
            mn, x, mx = ins
            unit = self._unit2(eqn, U.join, x, mn)
            unit, err = U.join(unit, mx.unit)
            if err:
                self._report("unit-mismatch", eqn, err)
            lo = min(max(x.lo, mn.lo), mx.lo)
            hi = min(max(x.hi, mn.hi), mx.hi)
            return [AbsVal(lo, hi, unit=unit, top=x.top and mn.top
                           and mx.top)]

        if name == "select_n":
            cases = ins[1:]
            out = cases[0]
            for c in cases[1:]:
                unit = self._unit2(eqn, U.join, out, c)
                lo, hi, top = join_bounds(out, c)
                out = AbsVal(lo, hi, unit=unit, top=top)
            return [out]

        if name in ("concatenate", "pad"):
            vals = ins if name == "concatenate" else ins[:2]
            lo = min(v.lo for v in vals)
            hi = max(v.hi for v in vals)
            us = {v.unit for v in vals if v.unit is not None}
            unit = us.pop() if len(us) == 1 else None
            return [AbsVal(lo, hi, unit=unit,
                           top=any(v.top for v in vals))]

        if name in ("and", "or", "xor", "not"):
            dtype = _aval_dtype(eqn.outvars[0])
            if dtype == "bool":
                return [AbsVal(0, 1)]
            if name == "and":
                nonneg = [v for v in ins if v.lo >= 0]
                if nonneg:
                    return [AbsVal(0, min(v.hi for v in nonneg))]
            if name in ("or", "xor") and all(v.lo >= 0 for v in ins):
                m = max(v.hi for v in ins)
                return [AbsVal(0, (1 << max(int(m), 1).bit_length()) - 1)]
            return [top_of(dtype).with_unit(None)]

        if name in ("shift_left", "shift_right_logical",
                    "shift_right_arithmetic"):
            a, s = ins
            dtype = _aval_dtype(eqn.outvars[0])
            if a.is_exact() and s.is_exact():
                x, sh = int(a.lo), int(s.lo)
                if name == "shift_left":
                    v = x << sh
                    rlo, rhi = dtype_range(dtype)
                    if v < rlo or v > rhi:
                        if dtype_kind(dtype) == "int":
                            self._report(
                                "overflow", eqn,
                                f"shift_left: {x} << {sh} exceeds "
                                f"{dtype}",
                            )
                        v = ((v - rlo) % (rhi - rlo + 1)) + rlo
                else:
                    v = x >> sh
                return [AbsVal(v, v, unit=a.unit)]
            if a.lo >= 0 and s.lo >= 0 and name != "shift_left":
                return [AbsVal(int(a.lo) >> int(s.hi),
                               int(a.hi) >> int(s.lo), unit=a.unit,
                               top=a.top)]
            return [top_of(dtype)]

        if name == "convert_element_type":
            return [self._convert(eqn, ins[0])]

        if name == "bitcast_convert_type":
            return [top_of(_aval_dtype(eqn.outvars[0]))]

        if name == "iota":
            d = int(p["dimension"])
            return [AbsVal(0, max(int(p["shape"][d]) - 1, 0))]

        if name in ("argmax", "argmin"):
            axes = p.get("axes", ())
            n = 1
            for ax in axes:
                n *= int(eqn.invars[0].aval.shape[int(ax)])
            return [AbsVal(0, max(n - 1, 0))]

        if name in ("reduce_max", "reduce_min"):
            a = ins[0]
            return [AbsVal(a.lo, a.hi, unit=a.unit, top=a.top)]

        if name in ("reduce_and", "reduce_or"):
            return [AbsVal(0, 1)]

        if name == "reduce_sum":
            a = ins[0]
            n = max(_size(eqn.invars[0].aval.shape)
                    // max(_size(eqn.outvars[0].aval.shape), 1), 1)
            return [self._mk_arith(eqn, 0, n * a.lo, n * a.hi, a.unit,
                                   ins, f"reduce_sum(n={n})")]

        if name == "cumsum":
            a = ins[0]
            n = int(eqn.invars[0].aval.shape[int(p.get("axis", 0))])
            lo = min(a.lo, n * a.lo)
            hi = max(a.hi, n * a.hi)
            return [self._mk_arith(eqn, 0, lo, hi, a.unit, ins,
                                   f"cumsum(n={n})")]

        if name in ("cummax", "cummin"):
            a = ins[0]
            return [a]

        if name == "sort":
            return list(ins)

        if name == "gather":
            return [ins[0].with_unit(ins[0].unit)]

        if name == "dynamic_slice":
            return [ins[0]]

        if name in ("scatter", "dynamic_update_slice"):
            op = ins[0]
            upd = ins[-1] if name == "dynamic_update_slice" else ins[2]
            unit = self._unit2(eqn, U.join, op, upd)
            lo, hi, top = join_bounds(op, upd)
            return [AbsVal(lo, hi, unit=unit, top=top)]

        if name in ("scatter-add", "scatter_add"):
            op, upd = ins[0], ins[2]
            n = max(_size(eqn.invars[2].aval.shape), 1)
            unit = self._unit2(eqn, U.add, op, upd)
            lo = op.lo + min(0, n * upd.lo)
            hi = op.hi + max(0, n * upd.hi)
            return [self._mk_arith(eqn, 0, lo, hi, unit, (op, upd),
                                   f"scatter-add(n={n})")]

        if name in ("scatter-min", "scatter-max"):
            op, upd = ins[0], ins[2]
            unit = self._unit2(eqn, U.join, op, upd)
            lo, hi, top = join_bounds(op, upd)
            return [AbsVal(lo, hi, unit=unit, top=top)]

        if name == "dot_general":
            a, b = ins[0], ins[1]
            ((lc, _rc), _batch) = p["dimension_numbers"]
            k = 1
            for ax in lc:
                k *= int(eqn.invars[0].aval.shape[int(ax)])
            mlo, mhi = mul_bounds(a, b)
            unit = self._unit2(eqn, U.mul, a, b)
            return [self._mk_arith(eqn, 0, k * mlo, k * mhi, unit, ins,
                                   f"dot_general(k={k})")]

        if name in ("psum", "psum2", "psum_invariant"):
            a = ins[0]
            n = self.collective_n
            return [self._mk_arith(eqn, i, n * v.lo, n * v.hi, v.unit,
                                   ins, f"psum(n={n})")
                    for i, v in enumerate(ins)]

        if name in ("pmax", "pmin"):
            return list(ins)

        if name == "axis_index":
            return [AbsVal(0, self.collective_n - 1)]

        if name == "top_k":
            a = ins[0]
            n = int(eqn.invars[0].aval.shape[-1])
            return [_strip_rows(a), AbsVal(0, max(n - 1, 0))]

        if name in ("population_count", "clz"):
            return [AbsVal(0, 64)]

        if name == "is_finite":
            return [AbsVal(0, 1)]

        if name in ("floor", "ceil", "round_nearest_even", "round"):
            a = ins[0]
            f = math.floor if name == "floor" else math.ceil
            lo = a.lo if math.isinf(a.lo) else float(f(a.lo))
            hi = a.hi if math.isinf(a.hi) else float(f(a.hi))
            return [AbsVal(lo, hi, unit=a.unit)]

        if name in ("sqrt", "rsqrt", "exp", "log", "log1p", "expm1",
                    "logistic", "tanh", "erf", "sin", "cos", "pow",
                    "atan2", "nextafter", "square", "cbrt"):
            # Float-only transcendental surface: honest don't-know.
            return [AbsVal(-math.inf, math.inf)
                    for _ in eqn.outvars]

        # -- structured control flow --------------------------------------
        if name == "pjit" or (
            "jaxpr" in p and name in ("closed_call", "shard_map",
                                      "remat", "checkpoint")
        ):
            return self.walk(p["jaxpr"], ins)

        if name in ("custom_jvp_call", "custom_vjp_call") and \
                p.get("call_jaxpr") is not None:
            return self.walk(p["call_jaxpr"], ins)

        if name == "scan":
            return self._scan(eqn, ins)

        if name == "while":
            return self._while(eqn, ins)

        if name == "cond":
            outs: Optional[List[AbsVal]] = None
            for br in p["branches"]:
                o = self.walk(br, ins[1:])
                if outs is None:
                    outs = o
                else:
                    merged = []
                    for x, y in zip(outs, o):
                        lo, hi, top = join_bounds(x, y)
                        unit, _ = U.join(x.unit, y.unit)
                        merged.append(AbsVal(lo, hi, unit=unit, top=top))
                    outs = merged
            return outs or [top_of(_aval_dtype(v)) for v in eqn.outvars]

        if name == "pallas_call":
            # Opaque by contract: bodies are differentially pinned
            # elsewhere; outputs are unconstrained-of-dtype.
            return [top_of(_aval_dtype(v)) for v in eqn.outvars]

        if name not in self._unknown_seen:
            self._unknown_seen.add(name)
            self._report(
                "unknown-primitive", eqn,
                f"no interval transfer for primitive '{name}' — result "
                "treated as unconstrained (add a transfer function in "
                "tools/gubrange/absint.py)",
            )
        return [top_of(_aval_dtype(v)) for v in eqn.outvars]

    # -- helpers ----------------------------------------------------------

    def _check_negative_duration(self, eqn, a: AbsVal, b: AbsVal) -> None:
        for x, y in ((a, b), (b, a)):
            if U.is_epoch(x.unit) and not U.is_epoch(y.unit) and \
                    not y.top and y.lo < 0:
                self._report(
                    "negative-duration", eqn,
                    f"possibly-negative interval [{y.lo}, {y.hi}] "
                    f"({y.unit or 'unitless'}) added to an absolute "
                    f"timestamp ({x.unit})",
                )

    def _convert(self, eqn, a: AbsVal) -> AbsVal:
        src = _aval_dtype(eqn.invars[0])
        dst = _aval_dtype(eqn.outvars[0])
        sk, dk = dtype_kind(src), dtype_kind(dst)
        if dk == "bool":
            return AbsVal(0, 1)
        if dk == "float":
            # Int lineage entering float is saturation-safe end-to-end:
            # re-entry to int goes through the _trunc_i64 contract.
            return AbsVal(float(a.lo), float(a.hi), unit=a.unit)
        if sk == "float":
            lo, hi = trunc_to_int_bounds(a, dst)
            return AbsVal(lo, hi, unit=a.unit)
        rlo, rhi = dtype_range(dst)
        if a.lo >= rlo and a.hi <= rhi:
            return AbsVal(int(a.lo), int(a.hi), unit=a.unit, top=a.top)
        # Out-of-range int->int reinterpretation: the dtype-taint plane
        # (gubtrace) governs narrowing legality; range-wise it's the
        # full destination range.
        return AbsVal(rlo, rhi, unit=a.unit, top=a.top)

    def _scan(self, eqn, ins: List[AbsVal]) -> List[AbsVal]:
        p = eqn.params
        nc, ncarry = int(p["num_consts"]), int(p["num_carry"])
        length = int(p["length"])
        body = p["jaxpr"]
        consts = ins[:nc]
        carry = list(ins[nc:nc + ncarry])
        # Body sees per-iteration elements: axis 0 of each xs is
        # consumed, so a packed-row refinement there shifts down one
        # axis (and collapses if the scan axis WAS the row axis).
        xs = []
        for x in ins[nc + ncarry:]:
            if x.rows is not None:
                x = (_strip_rows(x) if x.rows_axis == 0
                     else replace(x, rows_axis=x.rows_axis - 1))
            xs.append(x)
        n_ys = len(eqn.outvars) - ncarry
        ys: List[Optional[AbsVal]] = [None] * n_ys

        def step(carry_in: List[AbsVal]) -> List[AbsVal]:
            outs = self.walk(body, consts + carry_in + xs)
            for i, y in enumerate(outs[ncarry:]):
                prev = ys[i]
                if prev is None:
                    ys[i] = y
                else:
                    lo, hi, top = join_bounds(prev, y)
                    unit, _ = U.join(prev.unit, y.unit)
                    ys[i] = AbsVal(lo, hi, unit=unit, top=top)
            return outs[:ncarry]

        if length <= _SCAN_UNROLL_CAP:
            for _ in range(length):
                carry = step(carry)
        else:
            stable = False
            for _ in range(_WHILE_FIXPOINT_CAP):
                nxt_raw = step(carry)
                nxt = []
                changed = False
                for cur, new in zip(carry, nxt_raw):
                    lo, hi, top = join_bounds(cur, new)
                    unit, _ = U.join(cur.unit, new.unit)
                    j = AbsVal(lo, hi, unit=unit, top=top)
                    changed = changed or j != cur
                    nxt.append(j)
                carry = nxt
                if not changed:
                    stable = True
                    break
            if not stable:
                carry = [
                    top_of(_aval_dtype(v))
                    for v in eqn.outvars[:ncarry]
                ]
                carry = step(carry)
        return carry + [
            y if y is not None else top_of(_aval_dtype(v))
            for y, v in zip(ys, eqn.outvars[ncarry:])
        ]

    def _while(self, eqn, ins: List[AbsVal]) -> List[AbsVal]:
        p = eqn.params
        nc, nb = int(p["cond_nconsts"]), int(p["body_nconsts"])
        body_consts = ins[nc:nc + nb]
        carry = list(ins[nc + nb:])
        for _ in range(_WHILE_FIXPOINT_CAP):
            out = self.walk(p["body_jaxpr"], body_consts + carry)
            nxt = []
            changed = False
            for cur, new in zip(carry, out):
                lo, hi, top = join_bounds(cur, new)
                unit, _ = U.join(cur.unit, new.unit)
                j = AbsVal(lo, hi, unit=unit, top=top)
                changed = changed or j != cur
                nxt.append(j)
            carry = nxt
            if not changed:
                return carry
        carry = [top_of(_aval_dtype(v)) for v in eqn.outvars]
        return self.walk(p["body_jaxpr"], body_consts + carry)
