"""Negative-control fixture: a kernel whose int64 algebra DOES wrap.

`fixture_mul_unclamped` charges `hits × cost` tokens with no clamp —
the exact bug class gubrange exists to rule out.  Its envelope
(tests/gubrange_fixtures/envelopes/fixture_mul_unclamped.json) declares
hits, cost ≤ 4e9, so the product reaches 1.6e19 > 2^63−1 and the
analysis must report an overflow; the corner witness then executes the
real kernel and the output is demonstrably negative.  The smoke script
and tests/test_gubrange.py assert BOTH, keeping the plane honest: if
the walker ever goes blind to real wraps, the control stops failing
and CI fails instead.
"""
from __future__ import annotations

from tools.gubtrace.core import BuiltKernel, KernelSpec

FIXTURE_B = 64


def _build() -> BuiltKernel:
    import jax
    import jax.numpy as jnp
    import numpy as np

    def fixture_mul_unclamped_impl(hits, cost, remaining):
        charge = hits * cost  # the bug: no saturation, can wrap
        return charge, remaining - charge

    jitted = jax.jit(fixture_mul_unclamped_impl)

    def sig():
        return (
            np.zeros(FIXTURE_B, np.int64),
            np.zeros(FIXTURE_B, np.int64),
            np.full(FIXTURE_B, 10**9, np.int64),
        )

    del jnp
    return BuiltKernel(
        fn=jitted,
        trace_fn=fixture_mul_unclamped_impl,
        signatures={"B64": sig},
        counters=(),
        expect_aliased=0,
    )


def fixture_specs():
    return [
        KernelSpec(
            name="fixture_mul_unclamped",
            where="tools/gubrange/fixture.py",
            build=_build,
            invariants=frozenset(),
        )
    ]
