"""Operational-envelope registry: declared input bounds per kernel.

Each registered kernel (tools/gubtrace/registry.py) carries one JSON
envelope in tools/gubrange/envelopes/<kernel>.json declaring, per input
leaf pattern, the operational bound the deployment promises (max limit,
max hits, max cost, max duration, horizon epoch, table geometry) and
the dimensional unit of the leaf.  The analysis seeds its intervals
from these declarations, so the theorem it proves is exactly "within
the declared envelope, no signed intermediate can wrap".

Exactness cuts both ways, like gubproof's expect_max: `expect_peak`
must EQUAL the analysis' observed peak (largest |bound| any signed-int
arithmetic intermediate reaches), and every finding budget must be
spent exactly — a declared envelope looser than what is provable is an
error, not slack.

Format:

  {
    "kernel": "apply_batch",
    "notes": "why these bounds are the deployment contract",
    "inputs": [
      {"pattern": ".hits", "unit": "count", "min": 0, "max": 1000000000}
    ],
    "budgets": {"float-div-zero": 3},
    "reasons": {"float-div-zero": "where(lim!=0, x/lim, 0) guards"},
    "expect_peak": "9223372036854775807"
  }

`pattern` matches as a substring of the jax.tree_util.keystr keypath of
the flattened args, first match wins — the same matching the gubtrace
counter taint uses.  `expect_peak` is a STRING because JSON numbers
lose integer precision past 2^53.  Every budget entry requires a
written reason.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from tools.gubrange.interval import (
    AbsVal,
    dtype_range,
    from_rows,
    top_of,
)

ENVELOPE_DIR = Path(__file__).resolve().parent / "envelopes"

# Finding classes an envelope may budget (with a reason).  "overflow"
# is deliberately absent: a provable wrap inside the envelope is never
# acceptable — fix the kernel, not the declaration.
BUDGETABLE = (
    "unbounded-arith",
    "int-div-zero",
    "float-div-zero",
    "negative-duration",
    "unit-mismatch",
)


@dataclass(frozen=True)
class InputRule:
    pattern: str
    min: int
    max: int
    unit: Optional[str] = None
    # Packed-stack refinement: per-index bounds along `rows_axis` for
    # the q-form kernels' 12-row int64 packs.  Each entry is
    # {"name": ..., "unit": ..., "min": ..., "max": ...} or
    # {"name": ..., "top": true} for a full-range lane (key_hash).
    rows: Optional[tuple] = None
    rows_axis: int = 0


@dataclass
class Envelope:
    kernel: str
    inputs: List[InputRule]
    budgets: Dict[str, int] = field(default_factory=dict)
    reasons: Dict[str, str] = field(default_factory=dict)
    expect_peak: Optional[int] = None
    notes: str = ""
    path: Optional[Path] = None

    def validate(self) -> List[str]:
        errs = []
        for cls in self.budgets:
            if cls not in BUDGETABLE:
                errs.append(
                    f"budget for non-budgetable class '{cls}' "
                    f"(budgetable: {', '.join(BUDGETABLE)})"
                )
            elif not self.reasons.get(cls, "").strip():
                errs.append(
                    f"budget '{cls}' has no written reason — every "
                    "licensed finding class must say why"
                )
        for cls in self.reasons:
            if cls not in self.budgets:
                errs.append(f"reason for unbudgeted class '{cls}'")
        for r in self.inputs:
            if r.min > r.max:
                errs.append(f"input '{r.pattern}': min {r.min} > max "
                            f"{r.max}")
        return errs


def load_envelope(path: Path) -> Envelope:
    raw = json.loads(path.read_text(encoding="utf-8"))
    peak = raw.get("expect_peak")
    return Envelope(
        kernel=raw["kernel"],
        inputs=[
            InputRule(
                pattern=i["pattern"], min=int(i["min"]), max=int(i["max"]),
                unit=i.get("unit"),
                rows=(tuple(i["rows"]) if i.get("rows") else None),
                rows_axis=int(i.get("rows_axis", 0)),
            )
            for i in raw.get("inputs", ())
        ],
        budgets={k: int(v) for k, v in raw.get("budgets", {}).items()},
        reasons=dict(raw.get("reasons", {})),
        expect_peak=int(peak) if peak is not None else None,
        notes=raw.get("notes", ""),
        path=path,
    )


def load_envelopes(env_dir: Path = ENVELOPE_DIR) -> Dict[str, Envelope]:
    out: Dict[str, Envelope] = {}
    for path in sorted(env_dir.glob("*.json")):
        env = load_envelope(path)
        out[env.kernel] = env
    return out


def save_peak(env: Envelope, peak: int) -> None:
    """--update: rewrite ONLY expect_peak, preserving the rest."""
    assert env.path is not None
    raw = json.loads(env.path.read_text(encoding="utf-8"))
    raw["expect_peak"] = str(peak)
    env.path.write_text(
        json.dumps(raw, indent=2) + "\n", encoding="utf-8"
    )


def seed(
    env: Envelope, args: tuple
) -> Tuple[List[AbsVal], List[str], List[str]]:
    """Interval+unit seeds for the flattened `args` leaves.

    Returns (seeds, unmatched_leaf_keys, unused_patterns):
    unmatched leaves become TOP of their dtype (arithmetic on them is a
    budgetable finding); declared patterns matching no leaf are stale.
    """
    import jax
    import numpy as np

    flat, _ = jax.tree_util.tree_flatten_with_path(args)
    seeds: List[AbsVal] = []
    unmatched: List[str] = []
    used = set()
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        dtype = np.asarray(leaf).dtype.name
        rule = next((r for r in env.inputs if r.pattern in key), None)
        if rule is None:
            if dtype == "bool":
                seeds.append(AbsVal(0, 1))
            else:
                seeds.append(top_of(dtype))
                unmatched.append(f"{key}:{dtype}")
            continue
        used.add(rule.pattern)
        rlo, rhi = dtype_range(dtype)
        if rule.rows is not None:
            row_vals = []
            for r in rule.rows:
                if r.get("top"):
                    row_vals.append(top_of(dtype, unit=r.get("unit")))
                else:
                    row_vals.append(AbsVal(
                        max(int(r["min"]), rlo), min(int(r["max"]), rhi),
                        unit=r.get("unit"),
                    ))
            seeds.append(from_rows(row_vals, rule.rows_axis))
            continue
        lo, hi = max(rule.min, rlo), min(rule.max, rhi)
        seeds.append(AbsVal(lo, hi, unit=rule.unit))
    unused = [r.pattern for r in env.inputs if r.pattern not in used]
    return seeds, unmatched, unused


def corner_args(env: Envelope, args: tuple, corner: str = "max") -> tuple:
    """Concrete args with every envelope-matched leaf at its bound
    corner — the witness input (see tools/gubrange/witness.py)."""
    import jax
    import numpy as np

    flat, treedef = jax.tree_util.tree_flatten_with_path(args)
    leaves = []
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        arr = np.asarray(leaf)
        rule = next((r for r in env.inputs if r.pattern in key), None)
        if rule is not None and arr.dtype.kind in "iu":
            rlo, rhi = dtype_range(arr.dtype.name)
            if rule.rows is not None:
                arr = arr.copy()
                for i, r in enumerate(rule.rows):
                    v = 0 if r.get("top") else (
                        r["max"] if corner == "max" else r["min"]
                    )
                    idx = [slice(None)] * arr.ndim
                    idx[rule.rows_axis] = i
                    arr[tuple(idx)] = min(max(int(v), rlo), rhi)
            else:
                v = rule.max if corner == "max" else rule.min
                arr = np.full_like(arr, min(max(v, rlo), rhi))
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves)
