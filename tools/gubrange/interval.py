"""Exact interval domain for the gubrange abstract interpreter.

An abstract value is a closed interval [lo, hi] in exact Python
arithmetic (unbounded ints for integer dtypes, IEEE floats with ±inf
for float dtypes), a dimensional unit tag (tools/gubrange/units.py),
and a TOP flag.

TOP means "unconstrained by the operational envelope" — e.g. a raw key
fingerprint, whose value genuinely spans the whole dtype.  TOP values
flow freely through moves, selects, comparisons and bit-masking (a
fingerprint may be hashed, bucketed, compared), but *signed integer
arithmetic* on a TOP operand is a finding: a sum or product over an
unconstrained int64 is exactly the silent-wrap class this plane
exists to rule out (it can only be licensed by an envelope budget with
a written reason).

UNSIGNED integer arithmetic is modular by definition (jnp uint64 is
arithmetic mod 2^64 — the multiply-shift row hashing in ops/sketch.py
relies on it), so uint ops never raise overflow findings; a result
that would leave the dtype range widens to the full range instead.

Floats carry honest interval endpoints (±inf included); float
arithmetic never "overflows" in the wrap sense, so the only float
finding is division by a zero-inclusive interval.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, replace
from typing import Optional, Tuple, Union

Num = Union[int, float]

INT_RANGES = {
    "int64": (-(2**63), 2**63 - 1),
    "int32": (-(2**31), 2**31 - 1),
    "int16": (-(2**15), 2**15 - 1),
    "int8": (-(2**7), 2**7 - 1),
    "uint64": (0, 2**64 - 1),
    "uint32": (0, 2**32 - 1),
    "uint16": (0, 2**16 - 1),
    "uint8": (0, 2**8 - 1),
    "bool": (0, 1),
}


def dtype_kind(dtype_name: str) -> str:
    """'int' | 'uint' | 'bool' | 'float' for a numpy dtype name."""
    if dtype_name == "bool":
        return "bool"
    if dtype_name.startswith("uint"):
        return "uint"
    if dtype_name.startswith("int"):
        return "int"
    return "float"


def dtype_range(dtype_name: str) -> Tuple[Num, Num]:
    if dtype_name in INT_RANGES:
        return INT_RANGES[dtype_name]
    return (-math.inf, math.inf)


@dataclass(frozen=True)
class AbsVal:
    """One abstract value: interval + unit + unconstrained flag.

    `rows`/`rows_axis` is the packed-stack refinement: the q-form
    kernels ship 12 semantically-distinct int64 rows in one array
    (key_hash beside hits beside flags), and one scalar interval over
    the whole pack would be uselessly wide.  When set, `rows[i]` bounds
    index i along `rows_axis`, and the top-level lo/hi/unit/top are
    ALWAYS their join — so every transfer that ignores rows is
    conservative-correct automatically; only slice/squeeze/scan
    propagate the refinement (see absint.py)."""

    lo: Num
    hi: Num
    unit: Optional[str] = None
    top: bool = False
    rows: Optional[tuple] = None
    rows_axis: int = 0

    def with_unit(self, unit: Optional[str]) -> "AbsVal":
        return replace(self, unit=unit)

    def is_exact(self) -> bool:
        return self.lo == self.hi

    def __str__(self) -> str:
        u = f" {self.unit}" if self.unit else ""
        t = " TOP" if self.top else ""
        r = f" rows@{self.rows_axis}x{len(self.rows)}" if self.rows else ""
        return f"[{self.lo}, {self.hi}]{u}{t}{r}"


def from_rows(rows, axis: int) -> AbsVal:
    """The pack value: top-level bounds/unit/top = join of the rows."""
    rows = tuple(rows)
    units = {r.unit for r in rows if r.unit is not None}
    return AbsVal(
        lo=min(r.lo for r in rows),
        hi=max(r.hi for r in rows),
        unit=units.pop() if len(units) == 1 else None,
        top=any(r.top for r in rows),
        rows=rows,
        rows_axis=axis,
    )


def top_of(dtype_name: str, unit: Optional[str] = None) -> AbsVal:
    lo, hi = dtype_range(dtype_name)
    return AbsVal(lo, hi, unit=unit, top=True)


def exact(v: Num, unit: Optional[str] = None) -> AbsVal:
    return AbsVal(v, v, unit=unit)


def join_bounds(a: AbsVal, b: AbsVal) -> Tuple[Num, Num, bool]:
    return (min(a.lo, b.lo), max(a.hi, b.hi), a.top or b.top)


# -- endpoint arithmetic (exact; no dtype clipping here) -----------------

def add_bounds(a: AbsVal, b: AbsVal) -> Tuple[Num, Num]:
    return (a.lo + b.lo, a.hi + b.hi)


def sub_bounds(a: AbsVal, b: AbsVal) -> Tuple[Num, Num]:
    return (a.lo - b.hi, a.hi - b.lo)


def _prod(x: Num, y: Num) -> Num:
    # 0 * inf is NaN in IEEE; the exact product's contribution is 0.
    if x == 0 or y == 0:
        return 0
    return x * y


def mul_bounds(a: AbsVal, b: AbsVal) -> Tuple[Num, Num]:
    cands = [
        _prod(a.lo, b.lo), _prod(a.lo, b.hi),
        _prod(a.hi, b.lo), _prod(a.hi, b.hi),
    ]
    return (min(cands), max(cands))


def _idiv(x: int, y: int) -> int:
    """C/Go/XLA integer division: truncation toward zero."""
    q = abs(x) // abs(y)
    return -q if (x < 0) != (y < 0) else q


def div_bounds_int(a: AbsVal, b: AbsVal) -> Tuple[int, int, bool]:
    """Truncating integer division; returns (lo, hi, zero_divisor).

    When the divisor interval includes 0, the quotient bounds are taken
    over the divisor with 0 excluded (the caller reports the finding;
    excluding 0 keeps the analysis usefully precise past it).
    """
    zero_div = b.lo <= 0 <= b.hi
    pieces = []
    if b.hi >= 1:
        pieces.append((max(b.lo, 1), b.hi))
    if b.lo <= -1:
        pieces.append((b.lo, min(b.hi, -1)))
    if not pieces:  # divisor is exactly [0, 0]
        return (0, 0, True)
    cands = []
    for plo, phi in pieces:
        for x in (a.lo, a.hi):
            for y in (plo, phi):
                cands.append(_idiv(int(x), int(y)))
        # The quotient magnitude peaks at the smallest |divisor|, which
        # is an interval endpoint here; numerator extremes included
        # above; 0 crossing of the numerator adds candidate 0.
        if a.lo < 0 < a.hi:
            cands.append(0)
    return (min(cands), max(cands), zero_div)


def div_bounds_float(a: AbsVal, b: AbsVal) -> Tuple[float, float, bool]:
    """IEEE float division bounds; returns (lo, hi, zero_divisor)."""
    zero_div = b.lo <= 0 <= b.hi
    pieces = []
    if b.hi > 0:
        pieces.append((b.lo if b.lo > 0 else math.nextafter(0, 1), b.hi))
    if b.lo < 0:
        pieces.append((b.lo, b.hi if b.hi < 0 else math.nextafter(0, -1)))
    if not pieces:
        # divisor identically 0: x/0 is ±inf (sign of numerator), 0/0 NaN
        return (-math.inf, math.inf, True)
    cands = []
    for plo, phi in pieces:
        for x in (float(a.lo), float(a.hi)):
            for y in (plo, phi):
                if x == 0.0:
                    cands.append(0.0)
                else:
                    try:
                        cands.append(x / y)
                    except (ZeroDivisionError, OverflowError):
                        cands.append(math.inf if (x > 0) == (y > 0)
                                     else -math.inf)
        if a.lo < 0 < a.hi:
            cands.append(0.0)
    if zero_div:
        # a non-zero numerator over a zero-crossing divisor reaches ±inf
        if a.hi > 0:
            cands.append(math.inf)
        if a.lo < 0:
            cands.append(-math.inf)
    return (min(cands), max(cands), zero_div)


def rem_bounds_int(a: AbsVal, b: AbsVal) -> Tuple[int, int, bool]:
    """lax.rem: sign follows the dividend, |r| < |b|."""
    zero_div = b.lo <= 0 <= b.hi
    mag = max(abs(int(b.lo)), abs(int(b.hi)))
    if mag == 0:
        return (0, 0, True)
    lo = -(mag - 1) if a.lo < 0 else 0
    hi = (mag - 1) if a.hi > 0 else 0
    # Tighter when the WHOLE dividend interval sits inside (-mag, mag):
    # there rem(x) == x.  (One-sided tightening is unsound — a dividend
    # interval [-1000, -1] over modulus 7 still reaches remainder 0 at
    # -7, so a.hi alone may not cap the bound.)
    if a.lo > -mag and a.hi < mag:
        lo = max(lo, int(a.lo))
        hi = min(hi, int(a.hi))
    return (lo, hi, zero_div)


def trunc_to_int_bounds(a: AbsVal, dtype_name: str) -> Tuple[int, int]:
    """float -> int convert under the _trunc_i64 saturation contract:
    truncation toward zero, out-of-range/±inf saturating at the dtype
    bounds, NaN -> 0 (pinned by tests/test_differential.py)."""
    rlo, rhi = dtype_range(dtype_name)
    lo = rlo if math.isinf(a.lo) or a.lo <= rlo else int(math.trunc(a.lo))
    hi = rhi if math.isinf(a.hi) or a.hi >= rhi else int(math.trunc(a.hi))
    return (max(lo, rlo), min(hi, rhi))
