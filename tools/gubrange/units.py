"""The time-unit dimension algebra for the gubrange jaxpr taint.

Unit tags are seeded from envelope metadata (tools/gubrange/envelopes)
and propagated through the same walk that carries intervals.  The
lattice is *gradual*: `None` means "no declared dimension" and is
polymorphic (literals, enums, flags, hashes) — a rule only fires when
BOTH operands carry a unit and the combination is dimensionally wrong.
That keeps the checker sharp on real confusions (ns+ms, epoch+epoch,
hits×duration) without drowning every unitless lane select in noise.

Tags:
  count, bytes            cardinalities
  ns, us, ms, s           durations at a granularity
  epoch_ns, epoch_ms, …   absolute timestamps at a granularity
  rate_ns, rate_ms, …     duration-per-count (leaky-bucket drip rate)

Rules (X is a duration granularity):
  X + X = X         epoch_X + X = epoch_X    epoch + epoch   ERROR
  epoch_X - epoch_X = X                      X - epoch       ERROR
  X × count = X     count × rate_X = X       X × Y           ERROR
  X / count = rate_X     X / rate_X = count  epoch / _       ERROR
  ns + ms (granularity mix in add/sub/compare/join)          ERROR

Each function returns (result_unit, error_reason_or_None).
"""
from __future__ import annotations

from typing import Optional, Tuple

DURATIONS = ("ns", "us", "ms", "s")
EPOCHS = tuple("epoch_" + d for d in DURATIONS)
RATES = tuple("rate_" + d for d in DURATIONS)
COUNTS = ("count", "bytes")
ALL_UNITS = DURATIONS + EPOCHS + RATES + COUNTS

U = Optional[str]
Res = Tuple[U, Optional[str]]


def is_epoch(u: U) -> bool:
    return u is not None and u.startswith("epoch_")


def is_duration(u: U) -> bool:
    return u in DURATIONS


def is_rate(u: U) -> bool:
    return u is not None and u.startswith("rate_")


def epoch_of(d: str) -> str:
    return "epoch_" + d


def duration_of(u: str) -> str:
    """The duration granularity inside an epoch_/rate_ tag."""
    return u.split("_", 1)[1]


def add(a: U, b: U) -> Res:
    if a is None:
        return (b, None)
    if b is None:
        return (a, None)
    if a == b:
        if is_epoch(a):
            return (a, f"{a} + {b}: adding two absolute timestamps")
        return (a, None)
    if is_epoch(a) and b == duration_of(a):
        return (a, None)
    if is_epoch(b) and a == duration_of(b):
        return (b, None)
    return (None, f"{a} + {b}")


def sub(a: U, b: U) -> Res:
    if b is None:
        return (a, None)
    if a is None:
        return (None, None)
    if a == b:
        if is_epoch(a):
            return (duration_of(a), None)
        return (a, None)
    if is_epoch(a) and b == duration_of(a):
        return (a, None)
    if is_epoch(b):
        return (None, f"{a} - {b}: subtracting an absolute timestamp "
                      "from a non-timestamp")
    return (None, f"{a} - {b}")


def mul(a: U, b: U) -> Res:
    if a is None:
        return (b, None)
    if b is None:
        return (a, None)
    if is_epoch(a) or is_epoch(b):
        return (None, f"{a} × {b}: scaling an absolute timestamp")
    for x, y in ((a, b), (b, a)):
        if x in COUNTS:
            if y in COUNTS:
                return ("count", None)
            if is_rate(y):
                return (duration_of(y), None)
            return (y, None)  # count × duration = duration
    return (None, f"{a} × {b}")


def div(a: U, b: U) -> Res:
    if b is None:
        return (a, None)
    if a is None:
        return (None, None)
    if is_epoch(a):
        return (None, f"{a} / {b}: dividing an absolute timestamp")
    if a == b:
        return ("count", None)  # ratio of like quantities
    if b in COUNTS:
        if is_duration(a):
            return ("rate_" + a, None)
        return (None, None)
    if is_rate(b) and a == duration_of(b):
        return ("count", None)
    if is_epoch(b):
        return (None, f"{a} / {b}: dividing by an absolute timestamp")
    return (None, f"{a} / {b}")


def join(a: U, b: U) -> Res:
    """select / min / max / clamp / scatter-merge: units must agree."""
    if a is None:
        return (b, None)
    if b is None:
        return (a, None)
    if a == b:
        return (a, None)
    return (None, f"{a} vs {b}: joining mixed units")


def compare(a: U, b: U) -> Optional[str]:
    if a is None or b is None or a == b:
        return None
    return f"{a} vs {b}: comparing mixed units"
