"""gubrange: interval abstract interpretation + time-unit taint.

The fourth static plane beside gubguard (source promises), gubtrace
(what XLA compiles), and gubproof (protocol algebra):

  ranges   walk every gubtrace-registered kernel's jaxpr with an exact
           interval domain seeded from its operational envelope
           (tools/gubrange/envelopes/<kernel>.json) and a dimensional
           unit tag, proving no signed intermediate can leave its dtype
           range, no division sees a zero-inclusive divisor, no
           negative interval feeds timestamp math, and no ns/ms/s/epoch
           confusion survives — then, for any violation, executing the
           real kernel at the interval corner so the report carries a
           concrete wrapped output (tools/gubrange/witness.py)
  suffix   the host-side AST pass: `_ns`/`_ms`/`_s` suffix discipline
           on wall-clock-derived names (delegates to the gubguard
           unit-suffix checker so pragmas and fixtures are shared)

Exactness cuts both ways: declared envelopes must match what the
analysis proves (expect_peak equality, budgets spent exactly), so the
registry can never rot into theater.  Run as:

    python -m tools.gubrange --strict

Exit 0 = clean, 1 = findings, 2 = usage error.  Like gubtrace, the
whole plane runs under JAX_PLATFORMS=cpu — no accelerator needed.
"""
from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from tools.gubrange.absint import RangeWalk
from tools.gubrange.envelope import (
    BUDGETABLE,
    ENVELOPE_DIR,
    Envelope,
    load_envelopes,
    save_peak,
    seed,
)
from tools.gubrange.witness import run_witness
from tools.gubtrace.core import Finding, KernelSpec

ALL_PHASES = ("ranges", "suffix")

# The registry's canonical mesh width (tools/gubtrace/registry.py
# N_SHARDS): psum-style collectives scale interval bounds by this.
COLLECTIVE_N = 8


def _analyze_kernel(
    spec: KernelSpec,
    env: Envelope,
    update: bool,
    dump_dir: Optional[Path],
) -> List[Finding]:
    import jax

    findings: List[Finding] = []

    def err(checker: str, msg: str, where: str = "",
            severity: str = "error") -> None:
        findings.append(Finding(
            checker=checker, kernel=spec.name, message=msg,
            severity=severity, where=where,
        ))

    for msg in env.validate():
        err("envelope", msg)

    try:
        built = spec.build()
    except Exception as e:
        err("trace", f"failed to build: {type(e).__name__}: {e}")
        return findings

    sig_name, make_args = next(iter(built.signatures.items()))
    args = make_args()
    seeds, _unmatched, unused = seed(env, args)
    for pat in unused:
        err("envelope",
            f"input pattern '{pat}' matches no leaf of signature "
            f"{sig_name} — stale declaration")

    try:
        closed = jax.make_jaxpr(built.trace_fn)(*args)
    except Exception as e:
        err("trace", f"failed to trace: {type(e).__name__}: {e}")
        return findings

    walk = RangeWalk(collective_n=COLLECTIVE_N)
    walk.walk(closed, seeds)

    by_cls: Dict[str, list] = {}
    for issue in walk.issues:
        by_cls.setdefault(issue.cls, []).append(issue)

    overflowed = False
    for issue in by_cls.pop("overflow", ()):
        overflowed = True
        err("overflow", issue.message, where=issue.where)
    for issue in by_cls.pop("unknown-primitive", ()):
        err("absint", issue.message, where=issue.where,
            severity="warning")
    for cls in BUDGETABLE:
        issues = by_cls.pop(cls, [])
        budget = env.budgets.get(cls, 0)
        if len(issues) > budget:
            for issue in issues:
                err(cls,
                    f"{issue.message} [observed {len(issues)} > "
                    f"budgeted {budget}]", where=issue.where)
        elif len(issues) < budget:
            err(cls,
                f"budget declares {budget} but the analysis finds only "
                f"{len(issues)} — shrink the declaration",
                severity="warning")
    for cls, issues in by_cls.items():  # never happens by construction
        for issue in issues:
            err(cls, issue.message, where=issue.where)

    if update and env.path is not None:
        if env.expect_peak != walk.peak:
            save_peak(env, walk.peak)
    elif env.expect_peak is None:
        err("peak",
            f"envelope declares no expect_peak; analysis proves "
            f"{walk.peak} (run with --update to record it)")
    elif env.expect_peak != walk.peak:
        direction = (
            "looser than provable — tighten it"
            if env.expect_peak > walk.peak
            else "below what is reachable"
        )
        err("peak",
            f"expect_peak {env.expect_peak} != proved peak "
            f"{walk.peak} ({direction})")

    if overflowed:
        report = run_witness(built, env, sig_name)
        if report:
            err("witness", report)

    if dump_dir is not None and any(
        f.severity == "error" for f in findings
    ):
        dump_dir.mkdir(parents=True, exist_ok=True)
        (dump_dir / f"{spec.name}.json").write_text(json.dumps({
            "kernel": spec.name,
            "signature": sig_name,
            "peak": str(walk.peak),
            "issues": [i.__dict__ for i in walk.issues],
            "findings": [f.__dict__ for f in findings],
        }, indent=2) + "\n", encoding="utf-8")
    return findings


def run(
    select: Optional[Sequence[str]] = None,
    kernel: Optional[str] = None,
    root: Optional[Path] = None,
    update: bool = False,
    envelope_dir: Optional[Path] = None,
    specs: Optional[Sequence[KernelSpec]] = None,
    dump_dir: Optional[Path] = None,
) -> List[Finding]:
    """Run the selected phases; returns sorted findings."""
    root = root or Path.cwd()
    phases = list(select) if select else list(ALL_PHASES)
    unknown = [p for p in phases if p not in ALL_PHASES]
    if unknown:
        raise ValueError(
            f"unknown phases: {unknown} (have: {', '.join(ALL_PHASES)})"
        )

    findings: List[Finding] = []

    if "ranges" in phases:
        import jax

        # The kernels' own package does this on import; the fixture
        # specs (and any future out-of-tree spec list) must see the
        # same 64-bit world or every int64 bound silently halves.
        jax.config.update("jax_enable_x64", True)
        if specs is None:
            from tools.gubtrace.registry import specs as registry_specs

            specs = registry_specs()
        envelopes = load_envelopes(envelope_dir or ENVELOPE_DIR)
        if kernel is not None:
            wanted = {k.strip() for k in kernel.split(",") if k.strip()}
            missing = wanted - {s.name for s in specs}
            if missing:
                raise ValueError(
                    f"unknown kernels: {sorted(missing)}"
                )
            specs = [s for s in specs if s.name in wanted]
        analyzed = set()
        for spec in specs:
            analyzed.add(spec.name)
            env = envelopes.get(spec.name)
            if env is None:
                findings.append(Finding(
                    checker="envelope", kernel=spec.name,
                    message=(
                        "no operational envelope — add "
                        f"tools/gubrange/envelopes/{spec.name}.json"
                    ),
                ))
                continue
            findings.extend(
                _analyze_kernel(spec, env, update, dump_dir)
            )
        if kernel is None:
            for name in sorted(set(envelopes) - analyzed):
                findings.append(Finding(
                    checker="envelope", kernel=name,
                    message=(
                        "envelope has no registered kernel — stale "
                        f"file {envelopes[name].path}"
                    ),
                ))

    if "suffix" in phases:
        from tools.gubguard import run as gubguard_run

        for f in gubguard_run(
            [str(root / "gubernator_tpu")],
            select=["unit-suffix"], root=root,
        ):
            findings.append(Finding(
                checker=f.checker, kernel="-", message=f.message,
                severity=f.severity, where=f"{f.path}:{f.line}",
            ))

    findings.sort(key=lambda f: (f.kernel, f.checker, f.message))
    return findings
