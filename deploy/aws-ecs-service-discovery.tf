# gubernator-tpu on AWS ECS with Cloud Map (DNS) peer discovery — the
# analog of the reference's examples/aws-ecs-service-discovery-deployment.
#
# Peers find each other through an AWS Cloud Map private DNS namespace:
# every task registers an A record under gubernator.<namespace>, and the
# daemon's DNS discovery (GUBER_PEER_DISCOVERY_TYPE=dns) polls that name.
# Adjust image/cpu/memory for your TPU-host-adjacent instance type; the
# daemon itself is CPU-only when pointed at a remote JAX backend.

variable "vpc_id" { type = string }
variable "subnet_ids" { type = list(string) }
variable "cluster_arn" { type = string }
variable "image" {
  type    = string
  default = "ghcr.io/example/gubernator-tpu:latest"
}

resource "aws_service_discovery_private_dns_namespace" "guber" {
  name = "guber.local"
  vpc  = var.vpc_id
}

resource "aws_service_discovery_service" "guber" {
  name = "gubernator"
  dns_config {
    namespace_id   = aws_service_discovery_private_dns_namespace.guber.id
    routing_policy = "MULTIVALUE"
    dns_records {
      type = "A"
      ttl  = 10
    }
  }
  health_check_custom_config { failure_threshold = 1 }
}

resource "aws_ecs_task_definition" "guber" {
  family                   = "gubernator-tpu"
  network_mode             = "awsvpc"
  requires_compatibilities = ["FARGATE"]
  cpu                      = 1024
  memory                   = 4096
  container_definitions = jsonencode([{
    name      = "gubernator-tpu"
    image     = var.image
    essential = true
    portMappings = [
      { containerPort = 1051, protocol = "tcp" }, # gRPC
      { containerPort = 1050, protocol = "tcp" }, # HTTP/REST + /metrics
    ]
    environment = [
      { name = "GUBER_GRPC_ADDRESS", value = "0.0.0.0:1051" },
      { name = "GUBER_HTTP_ADDRESS", value = "0.0.0.0:1050" },
      { name = "GUBER_PEER_DISCOVERY_TYPE", value = "dns" },
      { name = "GUBER_DNS_FQDN", value = "gubernator.guber.local" },
      { name = "GUBER_DNS_POLL_INTERVAL", value = "10" },
    ]
    healthCheck = {
      command  = ["CMD-SHELL", "gubernator-tpu-healthcheck || exit 2"]
      interval = 10
      retries  = 3
    }
  }])
}

resource "aws_ecs_service" "guber" {
  name            = "gubernator-tpu"
  cluster         = var.cluster_arn
  task_definition = aws_ecs_task_definition.guber.arn
  desired_count   = 3
  launch_type     = "FARGATE"
  network_configuration {
    subnets = var.subnet_ids
  }
  service_registries {
    registry_arn = aws_service_discovery_service.guber.arn
  }
}
