"""Headline benchmark: rate-limit decisions/sec on one chip at 10M keys.

Measures the steady-state device hot path (ops/step.py apply_batch): a
2^24-slot table (~16.7M slots, 8-way buckets) under a 10M-key workload,
mixed token/leaky bucket, batch of 262144 decisions per step
(BENCH_BATCH overrides).  The batch size is the framework's operating
point, not a workload property — the service's maximal-merge drains
feed steps whatever is queued, and per-step launch overhead amortizes
with batch until HBM bandwidth binds: measured r4, 32k -> ~0.27-0.39B,
131k -> ~1.1-1.4B, 262k -> ~2.4-3.2B decisions/s (~550GB/s of bucket
traffic, comfortably under v5e's ~819GB/s); 512k+ flirts with
saturation and >=1M lanes faulted the chip, so the default stays at
262144.  State exactness at this batch is asserted by the differential
suite and was spot-verified on-chip (remaining == limit - steps).

Two metrics, KERNEL and FED:

- kernel: pre-staged device-resident batches, responses left on device
  (one sync per 16 steps) — the chip's decision capability with feeding
  excluded.
- fed: every step uploads a fresh packed [12, B] request array and
  fetches the packed [9, B] response via apply_batch_packed_q at the
  SERVICE-DRAIN lane count (B = BENCH_FED_BATCH, default 4096 — the
  shape the daemon's coalesced merges actually dispatch), pipelined
  with double buffering — what a served workload can realize THROUGH
  THIS RIG'S HOST LINK.
  At 4096 lanes the per-step traffic is small (~0.7MB at 168
  bytes/decision), so per-sync LATENCY dominates: on the axon tunnel
  (~70-300ms per round trip) the fed number is ~4096/RTT ≈ 0.01-0.06M
  decisions/s and measures the tunnel, not the chip.  A co-located
  host pays ~30us upload + ~25us fetch (PCIe gen3 x16) against a
  measured ~54us small-shape step exec, so double-buffered fed is
  exec-bound at roughly 4096/54us ≈ 75M decisions/s — above the
  12.5M/chip baseline; BENCH_FED_BATCH scales the point.

The north-star target (BASELINE.json) is >=50M decisions/sec on a v5e-4,
i.e. 12.5M decisions/sec/chip; `vs_baseline` is value / 12.5e6.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} plus
fed_* companion fields (value stays the kernel metric; the fed fields
are the honest served-workload companion the README table pairs it
with).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_T0 = time.perf_counter()


def _phase(msg: str) -> None:
    """Progress to stderr (stdout carries only the single JSON line)."""
    sys.stderr.write("[bench %7.1fs] %s\n" % (time.perf_counter() - _T0, msg))
    sys.stderr.flush()


def main() -> None:
    # Total-budget watchdog (BENCH_TOTAL_BUDGET_S, default 2700s — far
    # above any observed full run, degraded tunnel included): the rig's
    # device tunnel can go fully dark, in which case the first device
    # call HANGS rather than erroring, and an unattended bench run would
    # never produce its JSON line.  A daemon timer prints a LABELED line
    # — the measured kernel value if that phase completed, else an
    # explicit device_unreachable error — and exits.  SIGALRM is not
    # used here because the fed phase owns it.
    import threading

    progress: dict = {"value": None}
    try:
        budget_s = float(os.environ.get("BENCH_TOTAL_BUDGET_S", 2700))
    except ValueError as e:
        raise SystemExit("BENCH_TOTAL_BUDGET_S must be a number: %s" % e)
    # Floor, not disable: the watchdog exists precisely for unattended
    # runs, and Timer(<=0) would fire before any work starts.
    budget_s = max(60.0, budget_s)

    def metric_line(value: float, **extra) -> dict:
        return {
            "metric": "rate_limit_decisions_per_sec_per_chip_10M_keys",
            "value": round(value, 1),
            "unit": "decisions/s",
            "vs_baseline": round(value / 12.5e6, 4),
            **extra,
        }

    # The artifact contract is ONE JSON line on stdout; the watchdog and
    # the normal path race near the budget boundary (Timer.cancel can't
    # stop an already-running callback), so emission is once-only.
    _emit_lock = threading.Lock()
    _emitted = [False]

    def emit_once(line: dict) -> bool:
        with _emit_lock:
            if _emitted[0]:
                return False
            _emitted[0] = True
        print(json.dumps(line), flush=True)
        return True

    def _total_watchdog() -> None:
        if progress["value"] is not None:
            line = metric_line(
                progress["value"],
                fed_error="total budget exceeded after kernel phase",
            )
        else:
            line = metric_line(0, error=(
                "device_unreachable: no phase completed within "
                "BENCH_TOTAL_BUDGET_S=%.0fs" % budget_s
            ))
        if emit_once(line):
            _phase("TOTAL BUDGET EXCEEDED — emitted watchdog line, exiting")
            os._exit(3)

    watchdog = threading.Timer(budget_s, _total_watchdog)
    watchdog.daemon = True
    watchdog.start()

    import jax

    from gubernator_tpu.ops.state import init_table
    from gubernator_tpu.ops.step import DeviceBatchJ, apply_batch

    num_slots = 1 << 24
    ways = 8
    batch = int(os.environ.get("BENCH_BATCH", 262_144))
    n_keys = int(os.environ.get("BENCH_KEYS", 10_000_000))
    n_staged = 8
    now0 = 1_700_000_000_000

    rng = np.random.default_rng(0)
    key_pool = rng.integers(1, 1 << 63, size=n_keys, dtype=np.int64)
    _phase("key pool generated")

    import functools

    import jax.numpy as jnp
    from jax import lax

    from gubernator_tpu.ops.step import apply_batch_impl

    def batch_from_keys(ks) -> DeviceBatchJ:
        """Expand a [batch] key vector into a full DeviceBatchJ on device —
        only the 8-byte/key key column ever crosses the host link."""
        active = ks != 0
        algo = (
            (ks.astype(jnp.uint64) >> jnp.uint64(7)) & jnp.uint64(1)
        ).astype(jnp.int32)
        limit = jnp.full((batch,), 1000, jnp.int64)
        zi = jnp.zeros((batch,), jnp.int64)
        zb = jnp.zeros((batch,), jnp.bool_)
        return DeviceBatchJ(
            key_hash=ks,
            hits=active.astype(jnp.int64),
            limit=limit,
            duration=jnp.full((batch,), 3_600_000, jnp.int64),
            algo=algo,
            burst=limit,
            reset_remaining=zb,
            is_greg=zb,
            greg_expire=zi,
            greg_duration=zi,
            active=active,
            use_cached=zb,
        )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def populate(tbl, keys2d, now_):
        """Insert every key row of keys2d [n_chunks, batch] in ONE device
        program (lax.scan) — one compile, one dispatch, no per-chunk host
        round-trips.  On remote-device rigs the per-dispatch tunnel cost
        varies wildly run to run; a python loop of 39 donated-table steps
        measured anywhere from seconds to tens of minutes."""

        def body(t, ks):
            t, _ = apply_batch_impl(t, batch_from_keys(ks), now_, ways)
            return t, None

        tbl, _ = lax.scan(body, tbl, keys2d)
        return tbl

    # Backend acquisition is the BENCH_r05 failure mode: when the TPU
    # tunnel is dark, jax.devices() raises (RuntimeError "Unable to
    # initialize backend ..." / JaxRuntimeError UNAVAILABLE).  That is
    # "no measurement possible", not a regression — emit a structured
    # skip artifact (rc=0) so the bench trajectory can tell the two
    # apart instead of recording an rc=1 crash.
    try:
        dev = jax.devices()[0]
    except Exception as e:  # noqa: BLE001 — any backend-init failure
        watchdog.cancel()
        emit_once({
            "metric": "rate_limit_decisions_per_sec_per_chip_10M_keys",
            "skipped": True,
            "reason": "device_unavailable: %s: %s"
                      % (type(e).__name__, e),
        })
        _phase("SKIPPED — no usable accelerator backend")
        return
    with jax.default_device(dev):
        table = init_table(num_slots)
    _phase("table initialized (%d slots)" % num_slots)

    now = np.int64(now0)
    # Misconfiguration must die BEFORE the populate phase — over a
    # degraded tunnel that phase can take minutes.  That includes the fed
    # companion's knob: parsed here so a bad value can't kill the run
    # after the kernel metric was already paid for.
    if n_keys < batch:
        raise SystemExit(
            "BENCH_KEYS (%d) must be >= BENCH_BATCH (%d) for unique "
            "per-batch sampling" % (n_keys, batch)
        )
    try:
        fed_batch = min(batch, int(os.environ.get("BENCH_FED_BATCH", 4096)))
    except ValueError as e:
        raise SystemExit("BENCH_FED_BATCH must be an integer: %s" % e)
    if fed_batch < 1:
        raise SystemExit("BENCH_FED_BATCH must be >= 1 (got %d)" % fed_batch)
    # Populate: insert all keys so the measured steady state runs against
    # a full-size live working set (~60% table load factor at defaults).
    n_chunks = (n_keys + batch - 1) // batch
    keys_padded = np.zeros(n_chunks * batch, dtype=np.int64)
    keys_padded[:n_keys] = key_pool
    keys2d = jax.device_put(keys_padded.reshape(n_chunks, batch), dev)
    jax.block_until_ready(keys2d)
    _phase("key columns uploaded (%.0f MB)" % (keys_padded.nbytes / 1e6))
    table = populate(table, keys2d, now)
    jax.block_until_ready(table.key)
    del keys2d
    _phase("populate done (%d keys, %d chunks)" % (n_keys, n_chunks))

    # Staged measurement batches: unique keys WITHIN each batch (the
    # steady state measured is the unique-key path, not the intra-batch
    # duplicate cascade), drawn uniformly from the full key pool.  Rows
    # are sampled independently so the property holds even when the pool
    # is smaller than n_staged * batch.
    staged_idx = np.stack([
        rng.choice(n_keys, size=batch, replace=False)
        for _ in range(n_staged)
    ])
    expand = jax.jit(batch_from_keys)
    staged = [
        expand(jax.device_put(key_pool[staged_idx[i]], dev))
        for i in range(n_staged)
    ]
    jax.block_until_ready(staged[-1].key_hash)
    _phase("staged batches built on device")
    for i in range(2):  # warm the measurement shape
        table, resp = apply_batch(table, staged[i], now, ways=ways)
    jax.block_until_ready(resp.status)
    _phase("warmup done")

    # Timed: run for ~2 seconds of wall time.
    iters = 0
    t0 = time.perf_counter()
    deadline = t0 + 2.0
    while time.perf_counter() < deadline:
        table, resp = apply_batch(
            table, staged[iters % n_staged], now, ways=ways
        )
        iters += 1
        if iters % 16 == 0:
            jax.block_until_ready(resp.status)
    jax.block_until_ready(resp.status)
    elapsed = time.perf_counter() - t0
    value = batch * iters / elapsed
    progress["value"] = value
    _phase("kernel metric done (%d iters, %.2fs)" % (iters, elapsed))

    # FED companion: fresh packed request upload + packed response fetch
    # per step (apply_batch_packed_q, the service-drain shape), double
    # buffered — dispatch step i+1 before fetching response i.  Non-fatal:
    # on a degraded tunnel the fetches can stall for minutes; the headline
    # kernel metric must still print, so failures/timeouts are reported in
    # fed_error instead of killing the run.
    from gubernator_tpu.ops.step import apply_batch_packed_q

    def pack_q(ks: np.ndarray, width: int) -> np.ndarray:
        q = np.zeros((12, width), dtype=np.int64)
        m = len(ks)
        q[0, :m] = ks
        q[1, :m] = 1
        q[2, :m] = 1000
        q[3, :m] = 3_600_000
        q[4, :m] = (ks.astype(np.uint64) >> np.uint64(7)) & np.uint64(1)
        q[5, :m] = 1000
        q[10, :m] = 1
        return q

    # Watchdog: the budget must fire even while a transfer is BLOCKED in
    # a C call (an inline clock check between iterations never runs while
    # np.asarray/device_put is stalled).  SIGALRM interrupts the wait and
    # raises in the main thread; best-effort — a C call that never yields
    # the GIL can still defer it, but slow-yet-progressing transfers are
    # interrupted where the inline check alone would not be reached.
    import signal

    import math

    # ceil: a fractional budget must not truncate to signal.alarm(0),
    # which would CANCEL the alarm instead of arming it.
    fed_budget_s = max(
        1, math.ceil(float(os.environ.get("BENCH_FED_BUDGET_S", 120)))
    )

    def _fed_alarm(signum, frame):  # noqa: ARG001
        raise TimeoutError("fed phase exceeded BENCH_FED_BUDGET_S")

    # The fed companion runs at the SERVICE-DRAIN shape (default 4096
    # lanes — what the daemon's coalesced merges actually dispatch,
    # bench_e2e.py's DeviceConfig), not the kernel metric's 262k
    # operating point: the metric exists to price per-step feeding, and
    # a 262k-lane upload is ~25MB/step — minutes per step on a degraded
    # tunnel, which is how the r4 fed phase timed out.
    # fed_batch was parsed/validated before the populate phase.
    bytes_per_decision = (12 + 9) * 8
    # Packed at fed_batch width directly: contiguous arrays for the timed
    # device_put loop (a [:, :fed_batch] slice of a full-batch pack would
    # re-copy a strided view every iteration).
    host_qs = [
        pack_q(key_pool[staged_idx[i][:fed_batch]], fed_batch)
        for i in range(n_staged)
    ]

    def run_fed() -> dict:
        """One fed-phase attempt under its own SIGALRM budget.  Reports a
        PARTIAL throughput if the budget (or the link) dies mid-loop with
        responses already fetched; raises only when nothing completed."""
        fetched = 0
        fed_iters = 0
        t0 = None
        t_last_fetch = None

        def result(elapsed: float, partial: bool) -> dict:
            fed_value = fed_batch * fetched / elapsed
            out = {
                "fed_decisions_per_sec": round(fed_value, 1),
                "fed_vs_baseline": round(fed_value / 12.5e6, 4),
                "fed_batch": fed_batch,
                "fed_link_bytes_per_decision": bytes_per_decision,
                "fed_implied_link_MBps": round(
                    fed_value * bytes_per_decision / 1e6, 1
                ),
                "fed_note": (
                    "per-step H2D request upload + D2H response fetch "
                    "(apply_batch_packed_q at the service-drain lane "
                    "count), double-buffered; on a remote-device tunnel "
                    "this measures the host link, not the chip — scale "
                    "by a co-located link's bandwidth via "
                    "fed_link_bytes_per_decision"
                ),
            }
            if partial:
                out["fed_partial"] = (
                    "fed budget/link expired mid-run; throughput is over "
                    "the %d responses fetched before expiry, timed to "
                    "the LAST successful fetch (the terminal stalled "
                    "transfer is excluded from the denominator)" % fetched
                )
            return out

        old_alarm = signal.signal(signal.SIGALRM, _fed_alarm)
        signal.alarm(fed_budget_s)
        try:
            # apply_batch_packed_q DONATES its table argument, so each
            # attempt steps a fresh on-device copy — the original `table`
            # stays alive for a retry after a failed first attempt.
            table2 = jax.tree_util.tree_map(jnp.copy, table)
            table2, r = apply_batch_packed_q(
                table2, jax.device_put(host_qs[0], dev), now, ways=ways
            )
            np.asarray(r)  # warm the shape + the transfer path
            _phase("fed warmup done")
            pending = None
            t0 = time.perf_counter()
            deadline = t0 + 2.0
            while time.perf_counter() < deadline or pending is not None:
                if time.perf_counter() < deadline:
                    q_dev = jax.device_put(
                        host_qs[fed_iters % n_staged], dev
                    )
                    table2, r = apply_batch_packed_q(
                        table2, q_dev, now, ways=ways
                    )
                    fed_iters += 1
                    nxt = r
                else:
                    nxt = None
                if pending is not None:
                    np.asarray(pending)  # previous step's full response
                    fetched += 1
                    t_last_fetch = time.perf_counter()
                pending = nxt
            fed_elapsed = time.perf_counter() - t0
            _phase(
                "fed metric done (%d iters, %.2fs)" % (fetched, fed_elapsed)
            )
            return result(fed_elapsed, partial=False)
        except Exception as e:  # noqa: BLE001 — fed is best-effort
            if fetched > 0 and t_last_fetch is not None:
                # Time to the LAST completed fetch — the terminal stall
                # (which can sit blocked until the alarm's full budget)
                # must not dilute the throughput of the work that DID
                # complete.
                elapsed = max(t_last_fetch - t0, 1e-9)
                _phase(
                    "fed metric PARTIAL after %r (%d fetched, %.2fs)"
                    % (e, fetched, elapsed)
                )
                return result(elapsed, partial=True)
            raise
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old_alarm)

    # One retry: the remote-device tunnel sporadically surfaces transient
    # UNAVAILABLE device errors between phases; a failed first attempt
    # with zero completed fetches is worth one more try before the
    # artifact records fed_error.  Failures never kill the kernel metric.
    fed: dict = {}
    for attempt in (1, 2):
        try:
            fed = run_fed()
            break
        except Exception as e:  # noqa: BLE001 — LABELED in the artifact
            _phase("fed attempt %d FAILED: %r" % (attempt, e))
            fed = {"fed_error": "%s: %s" % (type(e).__name__, e)}
            if attempt == 1:
                time.sleep(5)

    watchdog.cancel()
    emit_once(metric_line(value, **fed))


if __name__ == "__main__":
    main()
