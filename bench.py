"""Headline benchmark: rate-limit decisions/sec on one chip at 10M keys.

Measures the steady-state device hot path (ops/step.py apply_batch): a
2^24-slot table (~16.7M slots, 8-way buckets) under a 10M-key workload,
mixed token/leaky bucket, batch of 262144 decisions per step
(BENCH_BATCH overrides).  The batch size is the framework's operating
point, not a workload property — the service's maximal-merge drains
feed steps whatever is queued, and per-step launch overhead amortizes
with batch until HBM bandwidth binds: measured r4, 32k -> ~0.27-0.39B,
131k -> ~1.1-1.4B, 262k -> ~2.4-3.2B decisions/s (~550GB/s of bucket
traffic, comfortably under v5e's ~819GB/s); 512k+ flirts with
saturation and >=1M lanes faulted the chip, so the default stays at
262144.  State exactness at this batch is asserted by the differential
suite and was spot-verified on-chip (remaining == limit - steps).

Two metrics, KERNEL and FED:

- kernel: pre-staged device-resident batches, responses left on device
  (one sync per 16 steps) — the chip's decision capability with feeding
  excluded.
- fed: every step uploads a fresh packed [12, B] request array and
  fetches the packed [9, B] response (the apply_batch_packed_q shape
  the service drains actually use), pipelined with double buffering —
  what a served workload can realize THROUGH THIS RIG'S HOST LINK.
  168 bytes/decision of host<->device traffic bound it: on the axon
  tunnel (~16-20 MB/s effective, ~70ms/sync) the fed number measures
  the tunnel, not the chip — the line reports the implied link
  bandwidth so a co-located reader can scale it (PCIe gen3 x16
  ~13 GB/s => ~75M decisions/s link-bound at the same batch).

The north-star target (BASELINE.json) is >=50M decisions/sec on a v5e-4,
i.e. 12.5M decisions/sec/chip; `vs_baseline` is value / 12.5e6.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} plus
fed_* companion fields (value stays the kernel metric; the fed fields
are the honest served-workload companion the README table pairs it
with).
"""
from __future__ import annotations

import json
import os
import time

import numpy as np


def main() -> None:
    import jax

    from gubernator_tpu.ops.state import init_table
    from gubernator_tpu.ops.step import DeviceBatchJ, apply_batch

    num_slots = 1 << 24
    ways = 8
    batch = int(os.environ.get("BENCH_BATCH", 262_144))
    n_keys = 10_000_000
    n_staged = 8
    now0 = 1_700_000_000_000

    rng = np.random.default_rng(0)
    key_pool = rng.integers(1, 1 << 63, size=n_keys, dtype=np.int64)

    def make_batch(ks: np.ndarray) -> DeviceBatchJ:
        pad = batch - len(ks)
        if pad:
            ks = np.concatenate([ks, np.zeros(pad, dtype=np.int64)])
        active = ks != 0
        algo = ((ks.astype(np.uint64) >> np.uint64(7)) & np.uint64(1)).astype(
            np.int32
        )
        limit = np.full(batch, 1000, dtype=np.int64)
        return DeviceBatchJ(
            key_hash=ks,
            hits=active.astype(np.int64),
            limit=limit,
            duration=np.full(batch, 3_600_000, dtype=np.int64),
            algo=algo,
            burst=limit,
            reset_remaining=np.zeros(batch, dtype=bool),
            is_greg=np.zeros(batch, dtype=bool),
            greg_expire=np.zeros(batch, dtype=np.int64),
            greg_duration=np.zeros(batch, dtype=np.int64),
            active=active,
            use_cached=np.zeros(batch, dtype=bool),
        )

    dev = jax.devices()[0]
    with jax.default_device(dev):
        table = init_table(num_slots)

    now = np.int64(now0)
    # Populate: insert all 10M keys so the measured steady state runs
    # against a full-size live working set (~60% table load factor).
    for s in range(0, n_keys, batch):
        db = DeviceBatchJ(
            *[jax.device_put(a, dev) for a in make_batch(key_pool[s:s + batch])]
        )
        table, resp = apply_batch(table, db, now, ways=ways)
    jax.block_until_ready(resp.status)

    # Staged measurement batches: unique keys per batch, drawn uniformly
    # from the full 10M-key pool (permutation slices).
    perm = rng.permutation(n_keys)
    staged = [
        DeviceBatchJ(
            *[
                jax.device_put(a, dev)
                for a in make_batch(key_pool[perm[i * batch: (i + 1) * batch]])
            ]
        )
        for i in range(n_staged)
    ]
    for i in range(2):  # warm the measurement shape
        table, resp = apply_batch(table, staged[i], now, ways=ways)
    jax.block_until_ready(resp.status)

    # Timed: run for ~2 seconds of wall time.
    iters = 0
    t0 = time.perf_counter()
    deadline = t0 + 2.0
    while time.perf_counter() < deadline:
        table, resp = apply_batch(
            table, staged[iters % n_staged], now, ways=ways
        )
        iters += 1
        if iters % 16 == 0:
            jax.block_until_ready(resp.status)
    jax.block_until_ready(resp.status)
    elapsed = time.perf_counter() - t0
    value = batch * iters / elapsed

    # FED companion: fresh packed request upload + packed response fetch
    # per step (apply_batch_packed_q, the service-drain shape), double
    # buffered — dispatch step i+1 before fetching response i.
    from gubernator_tpu.ops.step import apply_batch_packed_q

    def pack_q(ks: np.ndarray) -> np.ndarray:
        q = np.zeros((12, batch), dtype=np.int64)
        m = len(ks)
        q[0, :m] = ks
        q[1, :m] = 1
        q[2, :m] = 1000
        q[3, :m] = 3_600_000
        q[4, :m] = (ks.astype(np.uint64) >> np.uint64(7)) & np.uint64(1)
        q[5, :m] = 1000
        q[10, :m] = 1
        return q

    host_qs = [
        pack_q(key_pool[perm[i * batch: (i + 1) * batch]])
        for i in range(n_staged)
    ]
    table2, r = apply_batch_packed_q(
        table, jax.device_put(host_qs[0]), now, ways=ways
    )
    np.asarray(r)  # warm the shape + the transfer path
    fed_iters = 0
    pending = None
    t0 = time.perf_counter()
    deadline = t0 + 2.0
    while time.perf_counter() < deadline or pending is not None:
        if time.perf_counter() < deadline:
            q_dev = jax.device_put(host_qs[fed_iters % n_staged])
            table2, r = apply_batch_packed_q(table2, q_dev, now, ways=ways)
            fed_iters += 1
            nxt = r
        else:
            nxt = None
        if pending is not None:
            np.asarray(pending)  # the previous step's full response
        pending = nxt
    fed_elapsed = time.perf_counter() - t0
    fed_value = batch * fed_iters / fed_elapsed
    bytes_per_decision = (12 + 9) * 8
    print(
        json.dumps(
            {
                "metric": "rate_limit_decisions_per_sec_per_chip_10M_keys",
                "value": round(value, 1),
                "unit": "decisions/s",
                "vs_baseline": round(value / 12.5e6, 4),
                "fed_decisions_per_sec": round(fed_value, 1),
                "fed_vs_baseline": round(fed_value / 12.5e6, 4),
                "fed_link_bytes_per_decision": bytes_per_decision,
                "fed_implied_link_MBps": round(
                    fed_value * bytes_per_decision / 1e6, 1
                ),
                "fed_note": (
                    "per-step H2D request upload + D2H response fetch "
                    "(apply_batch_packed_q), double-buffered; on a "
                    "remote-device tunnel this measures the host link, "
                    "not the chip — scale by a co-located link's "
                    "bandwidth via fed_link_bytes_per_decision"
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
