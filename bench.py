"""Headline benchmark: rate-limit decisions/sec on one chip at 10M keys.

Measures the steady-state device hot path (ops/step.py apply_batch): a
2^24-slot table (~16.7M slots, 8-way buckets) under a 10M-key workload,
mixed token/leaky bucket, batch of 32768 decisions per step.

The north-star target (BASELINE.json) is >=50M decisions/sec on a v5e-4,
i.e. 12.5M decisions/sec/chip; `vs_baseline` is value / 12.5e6.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax

    from gubernator_tpu.ops.state import init_table
    from gubernator_tpu.ops.step import DeviceBatchJ, apply_batch

    num_slots = 1 << 24
    ways = 8
    batch = 32_768
    n_keys = 10_000_000
    n_staged = 8
    now0 = 1_700_000_000_000

    rng = np.random.default_rng(0)
    key_pool = rng.integers(1, 1 << 63, size=n_keys, dtype=np.int64)
    # Unique keys per batch (the kernel's unique-key-per-batch contract;
    # duplicate splitting is the host packer's job): disjoint permutation
    # slices of the pool.
    perm = rng.permutation(n_keys)

    def staged_batch(i: int) -> DeviceBatchJ:
        ks = key_pool[perm[i * batch: (i + 1) * batch]]
        algo = (rng.random(batch) < 0.5).astype(np.int32)
        limit = np.full(batch, 1000, dtype=np.int64)
        return DeviceBatchJ(
            key_hash=ks,
            hits=np.ones(batch, dtype=np.int64),
            limit=limit,
            duration=np.full(batch, 60_000, dtype=np.int64),
            algo=algo,
            burst=limit,
            reset_remaining=np.zeros(batch, dtype=bool),
            is_greg=np.zeros(batch, dtype=bool),
            greg_expire=np.zeros(batch, dtype=np.int64),
            greg_duration=np.zeros(batch, dtype=np.int64),
            active=np.ones(batch, dtype=bool),
        )

    dev = jax.devices()[0]
    staged = [
        DeviceBatchJ(*[jax.device_put(a, dev) for a in staged_batch(i)])
        for i in range(n_staged)
    ]
    with jax.default_device(dev):
        table = init_table(num_slots)

    now = np.int64(now0)
    # Warmup: compile + populate the table.
    for i in range(4):
        table, resp = apply_batch(table, staged[i % n_staged], now, ways=ways)
    jax.block_until_ready(resp.status)

    # Timed: run for ~2 seconds of wall time.
    iters = 0
    t0 = time.perf_counter()
    deadline = t0 + 2.0
    while time.perf_counter() < deadline:
        table, resp = apply_batch(
            table, staged[iters % n_staged], now, ways=ways
        )
        iters += 1
        if iters % 16 == 0:
            jax.block_until_ready(resp.status)
    jax.block_until_ready(resp.status)
    elapsed = time.perf_counter() - t0

    value = batch * iters / elapsed
    print(
        json.dumps(
            {
                "metric": "rate_limit_decisions_per_sec_per_chip_10M_keys",
                "value": round(value, 1),
                "unit": "decisions/s",
                "vs_baseline": round(value / 12.5e6, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
